"""Process entry point: `python -m tigerbeetle_tpu <command>`.

reference: src/tigerbeetle/main.zig (commands :146-186) + cli.zig. Commands:

  format     --cluster=N --replica=I --replica-count=N <path>
  start      --addresses=a:p,b:p,... --replica=I [--engine=device|kernel|oracle] <path>
  recover    <aof> <path>  |  --from-cluster --addresses=... <path>
  repl       --addresses=... [--cluster=N]
  benchmark  [--transfer-count=N] [--account-count=N]
  inspect    [--integrity] [--digest] <path>
  version
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_addresses(text: str) -> list[tuple[str, int]]:
    out = []
    for part in text.split(","):
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def cmd_format(args) -> int:
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT

    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout, create=True)
    Replica.format(storage, cluster=args.cluster, replica_id=args.replica,
                   replica_count=args.replica_count)
    storage.sync()
    storage.close()
    print(f"formatted {args.path}: cluster={args.cluster} "
          f"replica={args.replica}/{args.replica_count}")
    return 0


class _WallTime:
    def monotonic(self) -> int:
        import time

        return time.monotonic_ns()

    def realtime(self) -> int:
        import time

        return time.time_ns()


def cmd_start(args) -> int:
    # Shutdown rides a signal FLAG from the very top: a SIGINT landing
    # during storage open / warmup / journal recovery must still reach
    # the main loop as an orderly stop (and dump the trace), not die as
    # a KeyboardInterrupt mid-construction. The only remaining unsafe
    # window is the interpreter's own module imports before this line.
    import signal as _signal

    stop: list = []
    prev_int = _signal.signal(_signal.SIGINT, lambda *_: stop.append(1))
    prev_term = _signal.signal(_signal.SIGTERM, lambda *_: stop.append(1))
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .state_machine import StateMachine
    from .vsr.message_bus import MessageBus
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT

    addresses = _parse_addresses(args.addresses)
    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout)

    replica_holder: list = []

    def on_message(msg):
        replica_holder[0].on_message(msg)

    tracer = None
    if args.trace or args.statsd or args.metrics_port is not None:
        from .trace import StatsD, Tracer

        statsd = None
        if args.statsd:
            host, sep, port = args.statsd.rpartition(":")
            if not sep or not port.isdigit():
                print(f"error: --statsd expects host:port, got {args.statsd!r}")
                return 2
            statsd = StatsD(host or "127.0.0.1", int(port))
        # pid = replica id: merged cluster traces get one process track
        # per replica (trace/merge.py). --metrics-port implies a
        # recording tracer: the endpoint exposes its registry.
        tracer = Tracer(statsd=statsd, pid=args.replica,
                        emit_interval_s=args.trace_emit_interval)
    bus = MessageBus(cluster=args.cluster, on_message=on_message,
                     replica_addresses=addresses, replica_id=args.replica,
                     listen=True, listen_port=args.listen_port,
                     tracer=tracer)
    aof = None
    if args.aof:
        from .aof import AOF

        aof = AOF(args.aof)
    # Production capacities match the DeviceLedger defaults (the
    # static-allocation bound, reference: config.zig limits); --small
    # keeps test clusters light. Shared by the serving factory AND the
    # warmup so the pre-compiled executables always match serving shapes.
    a_cap = (1 << 12) if args.small else (1 << 17)
    t_cap = (1 << 14) if args.small else (1 << 21)
    replica = Replica(
        cluster=args.cluster, replica_id=args.replica,
        replica_count=len(addresses), storage=storage, bus=bus,
        time=_WallTime(), tracer=tracer, aof=aof,
        state_machine_factory=lambda: StateMachine(
            engine=args.engine, a_cap=a_cap, t_cap=t_cap))
    replica_holder.append(replica)
    if args.engine == "device":
        # Compile the serving kernels BEFORE accepting connections: the
        # first create_transfers compile (~10s+ cold) must not land on a
        # client request's timeout budget.
        from .ops.ledger import warmup_kernels

        warm_s = warmup_kernels(a_cap=a_cap, t_cap=t_cap)
        print(f"kernels warm in {warm_s:.1f}s", flush=True)
    metrics_server = None
    if args.metrics_port is not None:
        from .metrics import MetricsServer, render_prometheus
        from .trace import burn_rates, evaluate, load_objectives

        try:
            slo_cfg = load_objectives()
        except (OSError, ValueError) as e:
            print(f"warning: SLO objectives unavailable: {e}", flush=True)
            slo_cfg = None

        def _exposition() -> str:
            rows = burn = None
            if slo_cfg is not None:
                rows = evaluate(tracer, slo_cfg["objectives"],
                                emit_to=tracer)
                burn = burn_rates([rows], slo_cfg["burn_window_runs"],
                                  slo_cfg["burn_budget"])
            return render_prometheus(tracer, slo_rows=rows, burn=burn)

        metrics_server = MetricsServer(_exposition,
                                       port=args.metrics_port)
        print(f"metrics on http://127.0.0.1:{metrics_server.port}/metrics",
              flush=True)
    replica.open()
    print(f"replica {args.replica} listening on "
          f"{addresses[args.replica][0]}:{addresses[args.replica][1]} "
          f"(cluster={args.cluster}, engine={args.engine})", flush=True)
    # The reference main loop: tick + io.run_for_ns
    # (src/tigerbeetle/main.zig:522-525). Shutdown rides the signal
    # FLAG installed at the top of cmd_start, not KeyboardInterrupt: a
    # SIGINT delivered while the interpreter is inside a C callback
    # (e.g. JAX's gc hook) raises there and is swallowed as "exception
    # ignored in callback" — the loop would never see it and the
    # server would ignore the shutdown.
    try:
        last_commit = -1
        while not stop:
            bus.poll(0.01)
            replica.tick()
            if replica.commit_min != last_commit:
                # Progress marker: the vortex supervisor's shutdown
                # reads these from the replica log to wait for every
                # replica to catch up to the cluster commit level
                # before delivering SIGINT (a lagging backup stopped
                # mid-catch-up would dump a commit-free trace).
                last_commit = replica.commit_min
                print(f"commit={last_commit}", flush=True)
    except KeyboardInterrupt:
        pass  # belt and braces: a late-registered handler race
    finally:
        _signal.signal(_signal.SIGINT, prev_int)
        _signal.signal(_signal.SIGTERM, prev_term)
    if metrics_server is not None:
        metrics_server.close()
    if tracer is not None:
        tracer.flush_statsd()
        if args.trace:
            tracer.dump_chrome_trace(args.trace)
    return 0


def cmd_repl(args) -> int:
    from .repl import run_repl
    from .vsr.client import Client

    client = Client(cluster=args.cluster, client_id=args.client_id,
                    replica_addresses=_parse_addresses(args.addresses))
    try:
        run_repl(client)
    finally:
        client.close()
    return 0


def cmd_benchmark(args) -> int:
    import json

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from .benchmark import bench_config2, bench_config_zipfian

    batches = max(1, args.transfer_count // 8190)
    if args.zipfian:
        accepted, elapsed = bench_config_zipfian(
            batches, account_count=args.account_count, theta=args.theta)
    else:
        accepted, elapsed = bench_config2(
            batches, account_count=args.account_count)
    print(json.dumps({
        "load_accepted_tx_per_s": round(accepted / elapsed, 1),
        "transfers": accepted,
        "seconds": round(elapsed, 3),
    }))
    return 0


def _recover_from_cluster(args) -> int:
    """Rebuild a blank/lost data file from the cluster's live peers
    (reference: src/vsr/replica_reformat.zig): solicit the newest durable
    checkpoint over the state-sync path, install it staged (the
    superblock's sync_op record makes a crash mid-install restart the
    rebuild instead of leaving a half-written file), repair the WAL
    suffix through normal VSR repair, certify the installed grid with a
    full scrub tour, then exit 0 — `start` rejoins as a voter."""
    import signal as _signal
    import time as _time

    from .state_machine import StateMachine
    from .vsr.message_bus import MessageBus
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT

    if not args.addresses:
        print("error: recover --from-cluster requires --addresses")
        return 2
    addresses = _parse_addresses(args.addresses)
    if args.replica_count != len(addresses):
        print(f"error: --replica-count={args.replica_count} but "
              f"--addresses lists {len(addresses)} replicas")
        return 2
    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout, create=True)
    holder: list = []
    bus = MessageBus(cluster=args.cluster,
                     on_message=lambda m: holder[0].on_message(m),
                     replica_addresses=addresses, replica_id=args.replica,
                     listen=True, listen_port=args.listen_port)
    replica = Replica(
        cluster=args.cluster, replica_id=args.replica,
        replica_count=args.replica_count, storage=storage, bus=bus,
        time=_WallTime(),
        state_machine_factory=lambda: StateMachine(engine="oracle"))
    holder.append(replica)
    replica.open_rebuild()
    print(f"rebuild: replica {args.replica} rebuilding from cluster "
          f"{args.cluster} ({len(addresses) - 1} peers)", flush=True)
    stop: list = []
    prev_int = _signal.signal(_signal.SIGINT, lambda *_: stop.append(1))
    prev_term = _signal.signal(_signal.SIGTERM, lambda *_: stop.append(1))
    t0 = _time.monotonic()
    deadline = t0 + args.timeout_s if args.timeout_s else None
    last_progress, last_print = "", 0.0
    try:
        while not replica.rebuild_complete and not stop:
            bus.poll(0.01)
            replica.tick()
            now = _time.monotonic()
            progress = replica.rebuild_progress()
            if progress != last_progress and now - last_print >= 0.2:
                last_progress, last_print = progress, now
                print(f"rebuild: {progress}", flush=True)
            if deadline is not None and now > deadline:
                print(f"rebuild: TIMED OUT after {args.timeout_s:.0f}s "
                      f"({progress})", flush=True)
                return 1
    finally:
        _signal.signal(_signal.SIGINT, prev_int)
        _signal.signal(_signal.SIGTERM, prev_term)
        bus.close()
        storage.sync()
        storage.close()
    if not replica.rebuild_complete:
        print(f"rebuild: interrupted ({replica.rebuild_progress()}); "
              "re-run recover --from-cluster to resume", flush=True)
        return 1
    replica.finish_rebuild()
    sb = replica.superblock
    print(f"rebuilt {args.path} from cluster: checkpoint op "
          f"{sb.op_checkpoint}, commit {replica.commit_min}, "
          f"{'state-synced' if replica._rebuild_synced else 'WAL-repaired'}"
          f", grid certified, in {_time.monotonic() - t0:.1f}s",
          flush=True)
    return 0


def cmd_recover(args) -> int:
    """Rebuild a fresh data file from an append-only file (reference:
    `tigerbeetle recover` replaying src/aof.zig frames) — or, with
    --from-cluster, from the cluster's live peers over state sync."""
    if args.from_cluster:
        if args.path is None:  # only one positional given
            args.path = args.aof
        if args.path is None:
            print("error: recover --from-cluster requires <path>")
            return 2
        return _recover_from_cluster(args)
    if args.aof is None or args.path is None:
        print("error: recover requires <aof> <path> "
              "(or --from-cluster <path>)")
        return 2
    from .aof import recover
    from .state_machine import StateMachine
    from .vsr.checksum import checksum
    from .vsr.durable import DurableState
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT
    from .vsr.superblock import SuperBlock

    sm = StateMachine(engine="oracle")
    applied = recover(args.aof, sm)
    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout, create=True)
    Replica.format(storage, cluster=args.cluster, replica_id=args.replica,
                   replica_count=args.replica_count)
    # Persist the replayed state as a fresh forest checkpoint (the recovered
    # oracle's dirty sets cover every object, so this writes everything).
    # The root carries the sessions trailer like every checkpoint root
    # (empty: AOF replay has no client sessions to preserve).
    import struct as _struct

    from .vsr.client_sessions import ClientSessions

    durable = DurableState(storage)
    sessions_blob = ClientSessions(storage).pack()
    root = (durable.checkpoint(sm.state)
            + sessions_blob + _struct.pack("<I", len(sessions_blob)))
    storage.write("snapshot", 0, root)
    sb = SuperBlock.load(storage)
    sb.snapshot_slot = 0
    sb.snapshot_size = len(root)
    sb.snapshot_checksum = checksum(root, domain=b"ckptroot")
    sb.store(storage)
    storage.sync()
    storage.close()
    print(f"recovered {applied} ops from {args.aof} into {args.path}")
    return 0


def _open_superblock(args):
    """(storage, superblock) for a path/--small pair, or (storage, None)
    with the shared no-quorum error printed."""
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT
    from .vsr.superblock import SuperBlock

    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout)
    sb = SuperBlock.load(storage)
    if sb is None:
        print("superblock: no quorum (unformatted or corrupt)")
    return storage, sb


def cmd_inspect(args) -> int:
    """Render superblock and WAL-slot dumps — against a healthy file OR
    a deliberately corrupted one: every bad checksum is FLAGGED in the
    output, never raised (an inspector that dies on the damage it exists
    to show is useless). Exit 1 when the file is unopenable (no
    superblock quorum / corrupt active checkpoint root)."""
    from .vsr.journal import Journal
    from .vsr.checksum import checksum
    from .vsr.storage import (SUPERBLOCK_COPIES, SUPERBLOCK_COPY_SIZE,
                              FileStorage, StorageLayout, TEST_LAYOUT)
    from .vsr.superblock import SuperBlock

    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout)
    # Per-copy superblock dump (the quorum rule tolerates torn/corrupt
    # copies — show which ones).
    for copy in range(SUPERBLOCK_COPIES):
        raw = storage.read(
            "superblock", copy * SUPERBLOCK_COPY_SIZE, SUPERBLOCK_COPY_SIZE)
        sb_copy = SuperBlock.unpack_copy(raw)
        if sb_copy is None:
            print(f"superblock copy {copy}: CORRUPT (bad checksum)")
        else:
            print(f"superblock copy {copy}: seq={sb_copy.sequence} "
                  f"view={sb_copy.view} "
                  f"checkpoint_op={sb_copy.op_checkpoint}")
    sb = SuperBlock.load(storage)
    root_ok = False
    if sb is None:
        print("superblock: no quorum (unformatted or corrupt)")
    else:
        print(f"superblock: cluster={sb.cluster} replica={sb.replica_id}/"
              f"{sb.replica_count} seq={sb.sequence} view={sb.view} "
              f"checkpoint_op={sb.op_checkpoint} commit_max={sb.commit_max}")
        if sb.sync_op:
            print(f"superblock: MID-REBUILD — state-sync install to op "
                  f"{sb.sync_op} was interrupted; only `recover "
                  "--from-cluster` may open this file")
        if sb.snapshot_size <= layout.snapshot_size_max:
            root = storage.read(
                "snapshot", sb.snapshot_slot * layout.snapshot_size_max,
                sb.snapshot_size)
            root_ok = checksum(root, domain=b"ckptroot") \
                == sb.snapshot_checksum
        print(f"snapshot: slot={sb.snapshot_slot} size={sb.snapshot_size} "
              f"root={'ok' if root_ok else 'CORRUPT (bad checksum)'}")
    journal = Journal(storage)
    try:
        slots = journal.recover()
    except Exception as e:  # defensive: the dump must outlive bad bytes
        print(f"journal: scan FAILED ({e!r})")
        slots = []
    clean = sum(1 for s in slots if s.state.value == "clean")
    faulty = sum(1 for s in slots if s.state.value == "faulty")
    print(f"journal: {clean} clean, {faulty} faulty, "
          f"{len(slots) - clean - faulty} unknown; op_max={journal.op_max()}")
    # WAL-slot dump: every slot holding a prepare (or failing to).
    for slot, s in enumerate(slots):
        if s.state.value == "clean" and s.header is None:
            continue  # formatted-empty
        if s.header is not None:
            where = f"op={s.header.op} view={s.header.view}"
        else:
            where = "no valid header"
        mark = {"clean": "ok", "faulty": "CORRUPT (bad checksum)",
                "unknown": "CORRUPT (unrecognizable)"}[s.state.value]
        print(f"wal slot {slot:4d}: {where} {mark}")
    if sb is None or not root_ok:
        return 1
    if args.digest:
        return _inspect_digest(storage, sb)
    if args.integrity:
        return _inspect_integrity(storage, sb)
    return 0


def _inspect_digest(storage, sb) -> int:
    """State-epoch digest of the checkpointed forest (ops/state_epoch):
    bit-identical across replicas at the same op_checkpoint, so two
    offline data files can be compared without byte-diffing grids — the
    vortex rebuild scenario's acceptance check."""
    from .ops.state_epoch import combine, oracle_state_digest
    from .vsr.durable import DurableState
    from .vsr.replica import _split_root

    root = storage.read(
        "snapshot", sb.snapshot_slot * storage.layout.snapshot_size_max,
        sb.snapshot_size)
    forest_root, _ = _split_root(root)
    try:
        state = DurableState(storage).open(forest_root, load_events=False)
    except Exception as e:
        print(f"digest: forest open FAILED ({e!r})")
        return 1
    comps = oracle_state_digest(state, a_cap=1 << 12)
    for k in sorted(comps):
        print(f"digest {k}: {comps[k]:016x}")
    print(f"digest: checkpoint_op={sb.op_checkpoint} "
          f"combined={combine(comps):016x}")
    return 0


def _inspect_integrity(storage, sb) -> int:
    """Full-file verification (reference: src/tigerbeetle/inspect_integrity
    .zig): checkpoint root checksum, every grid block reachable from the
    root (manifest -> index -> value, enumerated tolerantly so ALL faults
    are reported, not just the first), the session table's reply slots, and
    a state rebuild from the forest."""
    from .vsr import durable as durable_mod
    from .vsr.checksum import checksum
    from .vsr.client_sessions import ClientSessions
    from .vsr.durable import DurableState
    from .vsr.replica import _split_root

    faults = 0
    root = storage.read(
        "snapshot", sb.snapshot_slot * storage.layout.snapshot_size_max,
        sb.snapshot_size)
    if checksum(root, domain=b"ckptroot") != sb.snapshot_checksum:
        print("integrity: checkpoint root CORRUPT")
        return 1
    forest_root, sessions_blob = _split_root(root)

    # Walk the reachability graph block by block, continuing past faults.
    block_size = storage.layout.grid_block_size

    def read_block(address, size):
        raw = storage.read("grid", address.index * block_size, size)
        if checksum(raw, domain=b"blk") != address.checksum:
            return None
        return raw

    from .lsm.forest import chain_next, chain_payload

    blocks = checked = 0
    link = durable_mod.checkpoint_manifest(forest_root)
    manifest_payload = b""
    while link is not None:
        manifest_addr, manifest_size = link
        blocks += 1
        raw_chain = read_block(manifest_addr, manifest_size)
        if raw_chain is None:
            faults += 1
            print(f"integrity: manifest block {manifest_addr.index} CORRUPT")
            manifest_payload = None
            break
        checked += 1
        manifest_payload += chain_payload(raw_chain)
        link = chain_next(raw_chain)
    if manifest_payload is not None:
        for name, key_size, info in durable_mod.manifest_children(manifest_payload):
            blocks += 1
            index_raw = read_block(info.index_address, info.index_size)
            if index_raw is None:
                faults += 1
                print(f"integrity: grid block {info.index_address.index} "
                      f"({name} index) CORRUPT")
                continue
            checked += 1
            for address, size in durable_mod.index_children(index_raw, key_size):
                blocks += 1
                if read_block(address, size) is None:
                    faults += 1
                    print(f"integrity: grid block {address.index} "
                          f"({name}) CORRUPT")
                else:
                    checked += 1

    durable = DurableState(storage)
    try:
        state = durable.open(forest_root)
    except Exception as e:
        print(f"integrity: forest open FAILED ({e})")
        state = None
        faults += 1
    sessions = ClientSessions(storage)
    sessions.restore(sessions_blob)
    for client in sessions.missing_replies():
        # The slot may legitimately hold a NEWER reply than the checkpoint
        # recorded (post-checkpoint commits rewrite it; WAL replay
        # reconciles on open). Only garbage is a fault.
        from .vsr.header import Message

        entry = sessions.get(client)
        raw = storage.read(
            "client_replies",
            entry["slot"] * storage.layout.message_size_max,
            storage.layout.message_size_max)
        try:
            msg = Message.unpack(raw)
            newer_ok = msg.valid() and msg.header.client == client
        except Exception:
            newer_ok = False
        if not newer_ok:
            faults += 1
            print(f"integrity: reply slot for client {client} CORRUPT")
    state_summary = ("state unreadable" if state is None else
                     f"{len(state.accounts)} accounts, "
                     f"{len(state.transfers)} transfers")
    print(f"integrity: {checked}/{blocks} grid blocks valid, "
          f"{state_summary}, {len(sessions.entries)} sessions, "
          f"{faults} fault(s)")
    return 1 if faults else 0


def cmd_amqp(args) -> int:
    """CDC pump: poll a live cluster's change events, publish to an AMQP
    broker with confirms (reference: `tigerbeetle amqp`, src/cdc/runner.zig)."""
    import time as _time

    from .cdc import AmqpSink, CDCRunner
    from .types import ChangeEvent, ChangeEventsFilter, Operation
    from .vsr.client import Client

    client = Client(cluster=args.cluster, client_id=args.client_id,
                    replica_addresses=_parse_addresses(args.addresses))

    class _ClusterSource:
        def get_change_events(self, f: ChangeEventsFilter):
            raw = client.query(Operation.get_change_events, f)
            return [ChangeEvent.unpack(raw[i:i + 384])
                    for i in range(0, len(raw), 384)]

    host, sep, port = args.amqp.rpartition(":")
    if not sep or not port.isdigit() or not host:
        print(f"--amqp must be host:port, got {args.amqp!r}")
        return 1
    from .cdc import AmqpProgress, FileProgress

    amqp_kwargs = dict(user=args.user, password=args.password,
                       virtual_host=args.vhost)
    # Durable progress (reference: the broker-resident progress-tracker
    # queue, src/cdc/runner.zig:34): by default the watermark lives in
    # the broker and a restarted runner resumes exactly after the
    # confirmed stream; --timestamp-last overrides, --progress-file uses
    # a local sidecar instead. Built before the sink so a failed locker
    # declare strands no connection (and vice versa).
    progress_close = None
    if args.progress_file:
        progress = FileProgress(args.progress_file)
    else:
        progress = AmqpProgress(host, int(port), cluster=args.cluster,
                                **amqp_kwargs)
        progress_close = progress.close
    try:
        sink = AmqpSink(host, int(port), exchange=args.exchange,
                        cluster=args.cluster, lock=not args.no_lock,
                        **amqp_kwargs)
    except BaseException:
        if progress_close:
            progress_close()
        raise
    runner = CDCRunner(_ClusterSource(), sink, progress=progress)
    runner.recover()
    if args.timestamp_last:
        # Operator override (reference: recovery_mode .override): seed
        # the watermark AND persist it, so the next restart resumes from
        # the confirmed stream, not from the override again.
        runner.timestamp_processed = args.timestamp_last
        progress.store(args.timestamp_last)
    try:
        while True:
            n = runner.run_until_idle()
            if n:
                print(f"published {n} (total {runner.published}, "
                      f"watermark {runner.timestamp_processed})")
            if args.once:
                return 0
            _time.sleep(args.poll_interval)
    except KeyboardInterrupt:
        return 0
    finally:
        runner.close()
        sink.close()
        if progress_close:
            progress_close()
        client.close()


def cmd_fuzz(args) -> int:
    """Run a named fuzzer with a seed (reference: `zig build fuzz --
    <name> <seed>`, src/fuzz_tests.zig registry)."""
    from .testing import fuzz

    if args.name == "list":
        for name in fuzz.FUZZERS:
            print(name)
        return 0
    if args.name != "smoke" and args.name not in fuzz.FUZZERS:
        print(f"unknown fuzzer {args.name!r}; `fuzz list` shows them")
        return 1
    fuzz.run(args.name, args.seed, args.iterations)
    print(f"fuzz {args.name} seed={args.seed}: OK")
    return 0


def cmd_multiversion(args) -> int:
    """Inspect a data file's checkpoint release vs this binary
    (reference: `tigerbeetle multiversion` + the re-exec decision,
    src/multiversion.zig)."""
    from .multiversion import RELEASE, ReleaseTracker, release_str

    _storage, sb = _open_superblock(args)
    if sb is None:
        return 1
    compatible = ReleaseTracker().compatible(sb.release)
    print(f"binary release:     {release_str(RELEASE)}")
    print(f"data file release:  {release_str(sb.release)} "
          f"(checkpoint op {sb.op_checkpoint})")
    print(f"compatible:         {'yes' if compatible else 'NO — upgrade path required'}")
    return 0 if compatible else 1


def cmd_jaxhound(args) -> int:
    """Kernel compile-bloat report (reference analog: src/copyhound.zig —
    IR-level bloat hunting)."""
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .jaxhound import report

    try:
        lines = report(args.kernel)
    except KeyError as e:
        print(e.args[0])
        return 1
    for line in lines:
        print(line)
    return 0


def cmd_devhub(args) -> int:
    """Record bench results + render the metrics dashboard (reference:
    src/scripts/devhub.zig + devhub.tigerbeetle.com)."""
    from . import devhub

    if args.record:
        with open(args.record) as f:
            devhub.record(args.history, json.load(f))
    entries = devhub.load(args.history)
    regress = devhub.regressions(entries)
    n = devhub.render(args.history, args.out, cfo_dir=args.cfo_dir,
                      entries=entries, regress=regress)
    for key, r in regress.items():
        print(f"devhub: REGRESSION {key}: {r['latest']:,.0f} is "
              f"{r['ratio']:.2f}x of trailing median {r['baseline']:,.0f}")
    print(f"devhub: {n} runs -> {args.out}")
    # Nonzero on regression so CI can gate on it (reference: the devhub
    # run IS the nightly perf gate, src/scripts/devhub.zig:174-237).
    return 2 if regress and args.strict else 0


def cmd_cfo(args) -> int:
    """Continuous fuzzing orchestrator: interleave random single-
    component fuzzer runs with WHOLE-CLUSTER VOPR swarm seeds (random
    topology + fault config + audited workload), recording failing
    seeds and a results artifact (reference: src/scripts/cfo.zig —
    fleet machines run fuzzers AND VOPR 24/7, failing seeds pushed to
    devhubdb)."""
    import random as _random
    import time as _time

    from .testing import fuzz
    from .testing.chaos import TRAFFIC_SHAPES, run_chaos_seed
    from .testing.vopr import run_swarm_seed

    if args.kind == "chaos" and args.seed is not None and not args.max_runs:
        # `cfo --kind chaos --seed S` IS the documented reproduction
        # command for a failing chaos seed: one run of exactly S.
        args.max_runs = 1
    rng = (_random.Random(args.seed) if args.seed is not None
           else _random.SystemRandom())
    deadline = (_time.monotonic() + args.budget_s) if args.budget_s else None
    names = list(fuzz.FUZZERS)
    counts: dict = {}
    failing: list = []
    t0 = _time.monotonic()
    runs = failures = 0
    try:
        while deadline is None or _time.monotonic() < deadline:
            if args.kind in ("fuzz", "vopr", "chaos"):
                kind = args.kind
            else:
                # Mix: the cluster seeds are the expensive, high-yield
                # side; keep them a steady ~1/3 of the stream, with the
                # serving-chaos seeds a further ~1/6.
                roll = rng.random()
                kind = ("vopr" if roll < (1 / 3)
                        else "chaos" if roll < (1 / 2) else "fuzz")
            seed = (args.seed if args.seed is not None
                    and args.max_runs == 1 else rng.randrange(1 << 30))
            # Chaos traffic shape: explicit --traffic pins it; the
            # random stream interleaves the adversarial shapes with the
            # uniform workload about half the time (seed-deterministic).
            traffic = None
            if kind == "chaos":
                if getattr(args, "traffic", None):
                    traffic = args.traffic
                elif args.seed is None or args.max_runs != 1:
                    traffic = rng.choice((None, None, None)
                                         + TRAFFIC_SHAPES)
            name = kind if kind != "fuzz" else rng.choice(names)
            if kind == "chaos" and traffic:
                name = f"chaos:{traffic}"
            key = f"fuzz:{name}" if kind == "fuzz" else name
            try:
                if kind == "vopr":
                    run_swarm_seed(seed)
                elif kind == "chaos":
                    run_chaos_seed(seed, traffic=traffic)
                else:
                    fuzz.run(name, seed)
                runs += 1
                counts[key] = counts.get(key, 0) + 1
            except Exception as e:  # record and keep hunting
                failures += 1
                # Each record carries ITS OWN exact reproduction command
                # (the fuzzer name cannot be re-derived from the seed).
                repro = (
                    f"python -m tigerbeetle_tpu cfo --kind vopr "
                    f"--seed {seed} --max-runs 1" if kind == "vopr"
                    else f"python -m tigerbeetle_tpu cfo --kind chaos "
                    f"--seed {seed}"
                    + (f" --traffic {traffic}" if traffic else "")
                    if kind == "chaos"
                    else f"python -m tigerbeetle_tpu fuzz {name} {seed}")
                failing.append({"kind": kind, "name": name, "seed": seed,
                                "error": repr(e)[:300],
                                "reproduce": repro})
                line = f"{name} {seed} {e!r}"
                print(f"FAIL {line}\n  reproduce: {repro}", flush=True)
                if args.failures_file:
                    with open(args.failures_file, "a") as f:
                        f.write(line + "\n")
            if args.max_runs and runs + failures >= args.max_runs:
                break
    except KeyboardInterrupt:
        pass
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump({
                "runs_clean": runs, "runs_failing": failures,
                "elapsed_s": round(_time.monotonic() - t0, 1),
                "counts": dict(sorted(counts.items())),
                "failing": failing,
            }, f, indent=1)
            f.write("\n")
    print(f"cfo: {runs} clean, {failures} failing "
          f"(reproduce: python -m tigerbeetle_tpu fuzz <name> <seed> / "
          f"cfo --kind vopr --seed <seed> --max-runs 1)")
    return 1 if failures else 0


def cmd_clients(args) -> int:
    """Regenerate the Go/Node client packages (reference: the per-language
    codegen under src/clients/, run via `zig build clients:*`)."""
    from .clients import codegen

    written = codegen.write_out(args.out)
    for path in written:
        print(path)
    print(f"clients: {len(written)} files generated into {args.out}/")
    return 0


def cmd_version(args) -> int:
    from . import __version__

    print(f"tigerbeetle-tpu {__version__}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tigerbeetle_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("format")
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--replica-count", type=int, required=True)
    p.add_argument("--small", action="store_true",
                   help="small test layout (32-slot WAL)")
    p.add_argument("path")
    p.set_defaults(fn=cmd_format)

    p = sub.add_parser("start")
    p.add_argument("--addresses", required=True)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--engine", choices=("device", "kernel", "oracle"),
               default="device")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu)")
    p.add_argument("--small", action="store_true")
    p.add_argument("--trace", default=None,
                   help="dump a Chrome trace JSON here on shutdown")
    p.add_argument("--statsd", default=None,
                   help="emit DogStatsD metrics to host:port")
    p.add_argument("--trace-emit-interval", type=float, default=10.0,
                   help="seconds between StatsD timing-aggregate flushes "
                        "(gauges reset after each emit)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus text metrics on this HTTP "
                        "port (0 = ephemeral); implies a recording "
                        "tracer")
    p.add_argument("--aof", default=None,
                   help="append committed prepares to this AOF path")
    p.add_argument("--listen-port", type=int, default=None,
                   help="bind this port instead of the advertised one "
                        "(lets a fault proxy sit in front — vortex)")
    p.add_argument("path")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("recover")
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--replica-count", type=int, required=True)
    p.add_argument("--small", action="store_true")
    p.add_argument("--from-cluster", action="store_true",
                   help="rebuild the data file from live peers over "
                        "state sync instead of an AOF (usage: recover "
                        "--from-cluster --addresses=... <path>)")
    p.add_argument("--addresses", default=None,
                   help="cluster addresses (--from-cluster)")
    p.add_argument("--listen-port", type=int, default=None,
                   help="bind this port instead of the advertised one "
                        "(--from-cluster; lets a fault proxy sit in "
                        "front — vortex)")
    p.add_argument("--timeout-s", type=float, default=0,
                   help="--from-cluster: give up after this many "
                        "seconds (0 = wait forever)")
    p.add_argument("aof", nargs="?", default=None)
    p.add_argument("path", nargs="?", default=None)
    p.set_defaults(fn=cmd_recover)

    p = sub.add_parser("repl")
    p.add_argument("--addresses", required=True)
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--client-id", type=int, default=1)
    p.set_defaults(fn=cmd_repl)

    p = sub.add_parser("benchmark")
    p.add_argument("--transfer-count", type=int, default=100_000)
    p.add_argument("--account-count", type=int, default=10_000)
    p.add_argument("--zipfian", action="store_true",
                   help="Zipfian hot-account workload (reference default)")
    p.add_argument("--theta", type=float, default=0.99)
    p.add_argument("--platform", default=None)
    p.set_defaults(fn=cmd_benchmark)

    p = sub.add_parser("inspect")
    p.add_argument("--small", action="store_true")
    p.add_argument("--integrity", action="store_true",
                   help="verify every reachable grid block, reply slot, "
                   "and the state rebuild (exit 1 on any fault)")
    p.add_argument("--digest", action="store_true",
                   help="print the checkpointed forest's state-epoch "
                        "digest (bit-comparable across replicas at the "
                        "same checkpoint)")
    p.add_argument("path")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("amqp")
    p.add_argument("--addresses", required=True)
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--client-id", type=int, default=0xCDC)
    p.add_argument("--amqp", required=True, help="broker host:port")
    p.add_argument("--exchange", default="tb.cdc")
    p.add_argument("--user", default="guest")
    p.add_argument("--password", default="guest")
    p.add_argument("--vhost", default="/")
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="one pump pass, then exit")
    p.add_argument("--timestamp-last", type=int, default=0,
                   help="resume after this change-event timestamp")
    p.add_argument("--progress-file", default=None,
                   help="persist/resume the watermark in this file "
                        "(default: a durable queue in the broker)")
    p.add_argument("--no-lock", action="store_true",
                   help="skip the exclusive locker queue (allows "
                        "concurrent runners — duplicates likely)")
    p.set_defaults(fn=cmd_amqp)

    p = sub.add_parser("fuzz")
    p.add_argument("name", help="fuzzer name, 'smoke' (all briefly), "
                   "or 'list'")
    p.add_argument("seed", type=int, nargs="?", default=0)
    p.add_argument("--iterations", type=int, default=None)
    p.set_defaults(fn=cmd_fuzz)

    p = sub.add_parser("multiversion")
    p.add_argument("--small", action="store_true")
    p.add_argument("path")
    p.set_defaults(fn=cmd_multiversion)

    p = sub.add_parser("jaxhound")
    p.add_argument("--kernel", default=None)
    p.add_argument("--platform", default=None)
    p.set_defaults(fn=cmd_jaxhound)

    p = sub.add_parser("devhub")
    p.add_argument("--record", default=None,
                   help="bench JSON file to append to the history")
    p.add_argument("--history", default="devhub_history.jsonl")
    p.add_argument("--out", default="devhub.html")
    p.add_argument("--cfo-dir", default="cfo",
                   help="directory of CFO sweep artifacts to surface")
    p.add_argument("--strict", action="store_true",
                   help="exit 2 when a metric regressed vs its trailing "
                        "median (the nightly perf gate)")
    p.set_defaults(fn=cmd_devhub)

    p = sub.add_parser("cfo")
    p.add_argument("--budget-s", type=float, default=0,
                   help="stop after this many seconds (0 = run forever)")
    p.add_argument("--max-runs", type=int, default=0)
    p.add_argument("--kind", choices=["mix", "fuzz", "vopr", "chaos"],
                   default="mix",
                   help="mix (default): fuzzer registry + VOPR cluster "
                        "swarm + serving-chaos seeds interleaved; or "
                        "one side only (chaos = seeded device-fault "
                        "injection against the serving supervisor, "
                        "testing/chaos.py)")
    p.add_argument("--failures-file", default=None,
                   help="append failing (fuzzer, seed) pairs here")
    p.add_argument("--artifact", default=None,
                   help="write a JSON results artifact here")
    p.add_argument("--seed", type=int, default=None,
                   help="deterministic selection; with --max-runs 1 the "
                        "seed IS the run seed (reproduction)")
    p.add_argument("--traffic", default=None,
                   choices=["hot_skew", "pending_storm",
                            "open_close_burst"],
                   help="pin a named adversarial traffic shape for "
                        "chaos runs (testing/chaos.py TrafficShape); "
                        "default: the random stream interleaves shapes "
                        "with the uniform workload")
    p.set_defaults(fn=cmd_cfo)

    p = sub.add_parser("clients")
    p.add_argument("--out", default="clients",
                   help="output root (clients/go, clients/node)")
    p.set_defaults(fn=cmd_clients)

    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
