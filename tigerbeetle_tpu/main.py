"""Process entry point: `python -m tigerbeetle_tpu <command>`.

reference: src/tigerbeetle/main.zig (commands :146-186) + cli.zig. Commands:

  format     --cluster=N --replica=I --replica-count=N <path>
  start      --addresses=a:p,b:p,... --replica=I [--engine=kernel|oracle] <path>
  repl       --addresses=... [--cluster=N]
  benchmark  [--transfer-count=N] [--account-count=N]
  inspect    <path>
  version
"""

from __future__ import annotations

import argparse
import sys


def _parse_addresses(text: str) -> list[tuple[str, int]]:
    out = []
    for part in text.split(","):
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def cmd_format(args) -> int:
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT

    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout, create=True)
    Replica.format(storage, cluster=args.cluster, replica_id=args.replica,
                   replica_count=args.replica_count)
    storage.sync()
    storage.close()
    print(f"formatted {args.path}: cluster={args.cluster} "
          f"replica={args.replica}/{args.replica_count}")
    return 0


class _WallTime:
    def monotonic(self) -> int:
        import time

        return time.monotonic_ns()

    def realtime(self) -> int:
        import time

        return time.time_ns()


def cmd_start(args) -> int:
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .state_machine import StateMachine
    from .vsr.message_bus import MessageBus
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT

    addresses = _parse_addresses(args.addresses)
    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout)

    replica_holder: list = []

    def on_message(msg):
        replica_holder[0].on_message(msg)

    bus = MessageBus(cluster=args.cluster, on_message=on_message,
                     replica_addresses=addresses, replica_id=args.replica,
                     listen=True)
    tracer = None
    if args.trace or args.statsd:
        from .trace import StatsD, Tracer

        statsd = None
        if args.statsd:
            host, sep, port = args.statsd.rpartition(":")
            if not sep or not port.isdigit():
                print(f"error: --statsd expects host:port, got {args.statsd!r}")
                return 2
            statsd = StatsD(host or "127.0.0.1", int(port))
        tracer = Tracer(statsd=statsd)
    aof = None
    if args.aof:
        from .aof import AOF

        aof = AOF(args.aof)
    replica = Replica(
        cluster=args.cluster, replica_id=args.replica,
        replica_count=len(addresses), storage=storage, bus=bus,
        time=_WallTime(), tracer=tracer, aof=aof,
        state_machine_factory=lambda: StateMachine(engine=args.engine))
    replica_holder.append(replica)
    replica.open()
    print(f"replica {args.replica} listening on "
          f"{addresses[args.replica][0]}:{addresses[args.replica][1]} "
          f"(cluster={args.cluster}, engine={args.engine})", flush=True)
    # The reference main loop: tick + io.run_for_ns
    # (src/tigerbeetle/main.zig:522-525).
    try:
        while True:
            bus.poll(0.01)
            replica.tick()
    except KeyboardInterrupt:
        if tracer is not None and args.trace:
            tracer.dump_chrome_trace(args.trace)
        return 0


def cmd_repl(args) -> int:
    from .repl import run_repl
    from .vsr.client import Client

    client = Client(cluster=args.cluster, client_id=args.client_id,
                    replica_addresses=_parse_addresses(args.addresses))
    try:
        run_repl(client)
    finally:
        client.close()
    return 0


def cmd_benchmark(args) -> int:
    import json

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from .benchmark import bench_config2

    accepted, elapsed = bench_config2(
        max(1, args.transfer_count // 8190), account_count=args.account_count)
    print(json.dumps({
        "load_accepted_tx_per_s": round(accepted / elapsed, 1),
        "transfers": accepted,
        "seconds": round(elapsed, 3),
    }))
    return 0


def cmd_recover(args) -> int:
    """Rebuild a fresh data file from an append-only file (reference:
    `tigerbeetle recover` replaying src/aof.zig frames)."""
    from .aof import recover
    from .state_machine import StateMachine
    from .vsr.checksum import checksum
    from .vsr.durable import DurableState
    from .vsr.replica import Replica
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT
    from .vsr.superblock import SuperBlock

    sm = StateMachine(engine="oracle")
    applied = recover(args.aof, sm)
    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout, create=True)
    Replica.format(storage, cluster=args.cluster, replica_id=args.replica,
                   replica_count=args.replica_count)
    # Persist the replayed state as a fresh forest checkpoint (the recovered
    # oracle's dirty sets cover every object, so this writes everything).
    # The root carries the sessions trailer like every checkpoint root
    # (empty: AOF replay has no client sessions to preserve).
    import struct as _struct

    from .vsr.client_sessions import ClientSessions

    durable = DurableState(storage)
    sessions_blob = ClientSessions(storage).pack()
    root = (durable.checkpoint(sm.state)
            + sessions_blob + _struct.pack("<I", len(sessions_blob)))
    storage.write("snapshot", 0, root)
    sb = SuperBlock.load(storage)
    sb.snapshot_slot = 0
    sb.snapshot_size = len(root)
    sb.snapshot_checksum = checksum(root, domain=b"ckptroot")
    sb.store(storage)
    storage.sync()
    storage.close()
    print(f"recovered {applied} ops from {args.aof} into {args.path}")
    return 0


def cmd_inspect(args) -> int:
    from .vsr.journal import Journal
    from .vsr.storage import FileStorage, StorageLayout, TEST_LAYOUT
    from .vsr.superblock import SuperBlock

    layout = TEST_LAYOUT if args.small else StorageLayout()
    storage = FileStorage(args.path, layout=layout)
    sb = SuperBlock.load(storage)
    if sb is None:
        print("superblock: no quorum (unformatted or corrupt)")
        return 1
    print(f"superblock: cluster={sb.cluster} replica={sb.replica_id}/"
          f"{sb.replica_count} seq={sb.sequence} view={sb.view} "
          f"checkpoint_op={sb.op_checkpoint} commit_max={sb.commit_max}")
    print(f"snapshot: slot={sb.snapshot_slot} size={sb.snapshot_size}")
    journal = Journal(storage)
    slots = journal.recover()
    clean = sum(1 for s in slots if s.state.value == "clean")
    faulty = sum(1 for s in slots if s.state.value == "faulty")
    print(f"journal: {clean} clean, {faulty} faulty, "
          f"{len(slots) - clean - faulty} unknown; op_max={journal.op_max()}")
    return 0


def cmd_fuzz(args) -> int:
    """Run a named fuzzer with a seed (reference: `zig build fuzz --
    <name> <seed>`, src/fuzz_tests.zig registry)."""
    from .testing import fuzz

    if args.name == "list":
        for name in fuzz.FUZZERS:
            print(name)
        return 0
    if args.name != "smoke" and args.name not in fuzz.FUZZERS:
        print(f"unknown fuzzer {args.name!r}; `fuzz list` shows them")
        return 1
    fuzz.run(args.name, args.seed, args.iterations)
    print(f"fuzz {args.name} seed={args.seed}: OK")
    return 0


def cmd_version(args) -> int:
    from . import __version__

    print(f"tigerbeetle-tpu {__version__}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tigerbeetle_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("format")
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--replica-count", type=int, required=True)
    p.add_argument("--small", action="store_true",
                   help="small test layout (32-slot WAL)")
    p.add_argument("path")
    p.set_defaults(fn=cmd_format)

    p = sub.add_parser("start")
    p.add_argument("--addresses", required=True)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--engine", choices=("kernel", "oracle"), default="kernel")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. cpu)")
    p.add_argument("--small", action="store_true")
    p.add_argument("--trace", default=None,
                   help="dump a Chrome trace JSON here on shutdown")
    p.add_argument("--statsd", default=None,
                   help="emit DogStatsD metrics to host:port")
    p.add_argument("--aof", default=None,
                   help="append committed prepares to this AOF path")
    p.add_argument("path")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("recover")
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--replica-count", type=int, required=True)
    p.add_argument("--small", action="store_true")
    p.add_argument("aof")
    p.add_argument("path")
    p.set_defaults(fn=cmd_recover)

    p = sub.add_parser("repl")
    p.add_argument("--addresses", required=True)
    p.add_argument("--cluster", type=int, default=0)
    p.add_argument("--client-id", type=int, default=1)
    p.set_defaults(fn=cmd_repl)

    p = sub.add_parser("benchmark")
    p.add_argument("--transfer-count", type=int, default=100_000)
    p.add_argument("--account-count", type=int, default=10_000)
    p.add_argument("--platform", default=None)
    p.set_defaults(fn=cmd_benchmark)

    p = sub.add_parser("inspect")
    p.add_argument("--small", action="store_true")
    p.add_argument("path")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("fuzz")
    p.add_argument("name", help="fuzzer name, 'smoke' (all briefly), "
                   "or 'list'")
    p.add_argument("seed", type=int, nargs="?", default=0)
    p.add_argument("--iterations", type=int, default=None)
    p.set_defaults(fn=cmd_fuzz)

    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
