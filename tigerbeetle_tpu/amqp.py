"""AMQP 0.9.1 wire protocol: the CDC publisher's transport.

reference: src/amqp.zig + src/amqp/{protocol,spec,types}.zig — the
reference implements the protocol itself rather than depending on a client
library, and so does this module: frame codec, connection/channel
handshake, exchange/queue declaration, publisher confirms, and
basic.publish with content frames. Only the subset the CDC runner needs
(reference: src/cdc/runner.zig publishes change events with confirms).

Layout is sans-io at the codec level (encode_*/Frame.parse are pure) with
a small blocking socket client on top.
"""

from __future__ import annotations

import socket
import struct
from typing import Iterator, Optional

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

# (class, method) ids — AMQP 0.9.1 spec numbering.
CONNECTION_START = (10, 10)
CONNECTION_START_OK = (10, 11)
CONNECTION_TUNE = (10, 30)
CONNECTION_TUNE_OK = (10, 31)
CONNECTION_OPEN = (10, 40)
CONNECTION_OPEN_OK = (10, 41)
CONNECTION_CLOSE = (10, 50)
CONNECTION_CLOSE_OK = (10, 51)
CHANNEL_OPEN = (20, 10)
CHANNEL_OPEN_OK = (20, 11)
CHANNEL_CLOSE = (20, 40)
CHANNEL_CLOSE_OK = (20, 41)
EXCHANGE_DECLARE = (40, 10)
EXCHANGE_DECLARE_OK = (40, 11)
QUEUE_DECLARE = (50, 10)
QUEUE_DECLARE_OK = (50, 11)
QUEUE_BIND = (50, 20)
QUEUE_BIND_OK = (50, 21)
BASIC_PUBLISH = (60, 40)
BASIC_GET = (60, 70)
BASIC_GET_OK = (60, 71)
BASIC_GET_EMPTY = (60, 72)
BASIC_CLASS = 60
BASIC_ACK = (60, 80)
BASIC_NACK = (60, 120)
CONFIRM_SELECT = (85, 10)
CONFIRM_SELECT_OK = (85, 11)

RESOURCE_LOCKED = 405


class ProtocolError(Exception):
    pass


# ------------------------------------------------------------- primitives

def shortstr(s: str) -> bytes:
    raw = s.encode()
    assert len(raw) < 256
    return bytes([len(raw)]) + raw


def longstr(raw: bytes) -> bytes:
    return struct.pack(">I", len(raw)) + raw


def field_table(d: Optional[dict] = None) -> bytes:
    """Encode a field table (longstr values only — all this client emits)."""
    parts = []
    for key, value in (d or {}).items():
        if isinstance(value, str):
            parts.append(shortstr(key) + b"S" + longstr(value.encode()))
        elif isinstance(value, bool):
            parts.append(shortstr(key) + b"t" + (b"\x01" if value else b"\x00"))
        elif isinstance(value, int):
            parts.append(shortstr(key) + b"I" + struct.pack(">i", value))
        else:
            raise ProtocolError(f"unsupported table value {value!r}")
    body = b"".join(parts)
    return struct.pack(">I", len(body)) + body


class Reader:
    def __init__(self, raw: bytes):
        self.raw = raw
        self.pos = 0

    def u8(self) -> int:
        (v,) = struct.unpack_from(">B", self.raw, self.pos)
        self.pos += 1
        return v

    def u16(self) -> int:
        (v,) = struct.unpack_from(">H", self.raw, self.pos)
        self.pos += 2
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from(">I", self.raw, self.pos)
        self.pos += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from(">Q", self.raw, self.pos)
        self.pos += 8
        return v

    def shortstr(self) -> str:
        n = self.u8()
        s = self.raw[self.pos:self.pos + n]
        self.pos += n
        return s.decode()

    def longstr(self) -> bytes:
        n = self.u32()
        s = self.raw[self.pos:self.pos + n]
        self.pos += n
        return s

    def table(self) -> dict:
        size = self.u32()
        end = self.pos + size
        out = {}
        while self.pos < end:
            key = self.shortstr()
            kind = self.raw[self.pos:self.pos + 1]
            self.pos += 1
            if kind == b"S":
                out[key] = self.longstr().decode()
            elif kind == b"t":
                out[key] = self.u8() != 0
            elif kind == b"I":
                (v,) = struct.unpack_from(">i", self.raw, self.pos)
                self.pos += 4
                out[key] = v
            else:
                raise ProtocolError(f"unsupported table type {kind!r}")
        return out


# ------------------------------------------------------------------ frames

def frame(frame_type: int, channel: int, payload: bytes) -> bytes:
    return (struct.pack(">BHI", frame_type, channel, len(payload))
            + payload + bytes([FRAME_END]))


def method_frame(channel: int, class_method: tuple, args: bytes = b"") -> bytes:
    class_id, method_id = class_method
    return frame(FRAME_METHOD, channel,
                 struct.pack(">HH", class_id, method_id) + args)


def content_frames(channel: int, body: bytes,
                   frame_max: int = 128 * 1024) -> bytes:
    """Content header + body frames for one basic.publish."""
    header = struct.pack(">HHQH", BASIC_CLASS, 0, len(body), 0)
    out = [frame(FRAME_HEADER, channel, header)]
    chunk_max = frame_max - 8
    for off in range(0, len(body), chunk_max):
        out.append(frame(FRAME_BODY, channel, body[off:off + chunk_max]))
    return b"".join(out)


class Frame:
    def __init__(self, frame_type: int, channel: int, payload: bytes):
        self.type = frame_type
        self.channel = channel
        self.payload = payload

    @property
    def method(self) -> Optional[tuple]:
        if self.type != FRAME_METHOD:
            return None
        return struct.unpack_from(">HH", self.payload)

    def args(self) -> Reader:
        reader = Reader(self.payload)
        reader.pos = 4
        return reader

    @staticmethod
    def parse(buffer: bytearray) -> Optional["Frame"]:
        """Pop one frame off the buffer, or None if incomplete."""
        if len(buffer) < 8:
            return None
        frame_type, channel, size = struct.unpack_from(">BHI", buffer)
        total = 7 + size + 1
        if len(buffer) < total:
            return None
        if buffer[total - 1] != FRAME_END:
            raise ProtocolError("missing frame-end octet")
        payload = bytes(buffer[7:7 + size])
        del buffer[:total]
        return Frame(frame_type, channel, payload)


# ------------------------------------------------------------------ client

class AmqpClient:
    """Blocking publisher connection with confirms.

    reference: src/cdc/amqp.zig connection bring-up + publish path."""

    def __init__(self, host: str, port: int, *, virtual_host: str = "/",
                 user: str = "guest", password: str = "guest",
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.rx = bytearray()
        self.channel = 1
        self.confirm_mode = False
        self.publish_seq = 0
        self.outstanding: set[int] = set()  # unacked delivery tags
        self._handshake(virtual_host, user, password)

    # -------------------------------------------------------------- wires

    def _send(self, raw: bytes) -> None:
        self.sock.sendall(raw)

    def _recv_frame(self) -> Frame:
        while True:
            got = Frame.parse(self.rx)
            if got is not None:
                if got.type == FRAME_HEARTBEAT:
                    continue
                return got
            chunk = self.sock.recv(64 * 1024)
            if not chunk:
                raise ProtocolError("connection closed by broker")
            self.rx += chunk

    def _expect(self, class_method: tuple) -> Frame:
        got = self._recv_frame()
        if got.method != class_method:
            raise ProtocolError(
                f"expected {class_method}, got {got.method}")
        return got

    # ---------------------------------------------------------- handshake

    def _handshake(self, virtual_host: str, user: str, password: str) -> None:
        self._send(PROTOCOL_HEADER)
        self._expect(CONNECTION_START)
        response = b"\x00" + user.encode() + b"\x00" + password.encode()
        self._send(method_frame(0, CONNECTION_START_OK,
                                field_table({"product": "tigerbeetle-tpu"})
                                + shortstr("PLAIN") + longstr(response)
                                + shortstr("en_US")))
        tune = self._expect(CONNECTION_TUNE).args()
        channel_max = tune.u16()
        frame_max = tune.u32()
        tune.u16()  # broker-proposed heartbeat
        self.frame_max = frame_max or 128 * 1024
        # Negotiate heartbeats OFF (0): this client is a pump that may
        # legitimately idle between polls and sends no heartbeat frames.
        self._send(method_frame(0, CONNECTION_TUNE_OK, struct.pack(
            ">HIH", channel_max, self.frame_max, 0)))
        self._send(method_frame(0, CONNECTION_OPEN,
                                shortstr(virtual_host) + shortstr("") + b"\x00"))
        self._expect(CONNECTION_OPEN_OK)
        self._send(method_frame(self.channel, CHANNEL_OPEN, shortstr("")))
        self._expect(CHANNEL_OPEN_OK)

    # ------------------------------------------------------------ methods

    def exchange_declare(self, exchange: str, kind: str = "topic",
                         durable: bool = True) -> None:
        flags = 0b10 if durable else 0
        self._send(method_frame(
            self.channel, EXCHANGE_DECLARE,
            struct.pack(">H", 0) + shortstr(exchange) + shortstr(kind)
            + bytes([flags]) + field_table()))
        self._expect(EXCHANGE_DECLARE_OK)

    def queue_declare(self, queue: str, durable: bool = True,
                      exclusive: bool = False) -> None:
        """Declare a queue. `exclusive` queues belong to this connection
        and make a second declare by another connection fail with
        RESOURCE_LOCKED — the CDC runner's single-writer lock
        (reference: the locker queue, src/cdc/runner.zig:35)."""
        flags = (0b10 if durable else 0) | (0b100 if exclusive else 0)
        self._send(method_frame(
            self.channel, QUEUE_DECLARE,
            struct.pack(">H", 0) + shortstr(queue) + bytes([flags])
            + field_table()))
        got = self._recv_frame()
        if got.method == QUEUE_DECLARE_OK:
            return
        if got.method in (CONNECTION_CLOSE, CHANNEL_CLOSE):
            args = got.args()
            code = args.u16()
            text = args.shortstr()
            raise ProtocolError(f"queue.declare failed: {code} {text}")
        raise ProtocolError(f"expected queue.declare-ok, got {got.method}")

    def _apply_confirm(self, got: Frame) -> None:
        """Fold one broker basic.ack/nack into the outstanding confirm
        set (shared by wait_confirms and basic_get's absorption path)."""
        args = got.args()
        delivery_tag = args.u64()
        multiple = args.u8() & 1
        tags = ([t for t in self.outstanding if t <= delivery_tag]
                if multiple else
                [delivery_tag] if delivery_tag in self.outstanding
                else [])
        self.outstanding.difference_update(tags)
        if got.method == BASIC_NACK:
            raise ProtocolError(
                f"broker nacked delivery tag(s) {tags or [delivery_tag]}")

    def basic_get(self, queue: str,
                  no_ack: bool = False) -> Optional[tuple[int, bytes]]:
        """Synchronous single-message fetch: (delivery_tag, body), or
        None when the queue is empty — how the CDC runner recovers its
        progress watermark from the broker at startup (reference:
        runner.zig get_progress_message phase)."""
        self._send(method_frame(
            self.channel, BASIC_GET,
            struct.pack(">H", 0) + shortstr(queue)
            + bytes([1 if no_ack else 0])))
        got = self._recv_frame()
        # Outstanding publisher confirms may interleave ahead of the
        # get-ok on a shared channel; absorb them into the confirm set.
        while got.method in (BASIC_ACK, BASIC_NACK) and self.confirm_mode:
            self._apply_confirm(got)
            got = self._recv_frame()
        if got.method == BASIC_GET_EMPTY:
            return None
        if got.method != BASIC_GET_OK:
            raise ProtocolError(f"expected get-ok/empty, got {got.method}")
        args = got.args()
        delivery_tag = args.u64()
        args.u8()  # redelivered
        args.shortstr()  # exchange
        args.shortstr()  # routing key
        args.u32()  # message count
        header = self._recv_frame()
        if header.type != FRAME_HEADER:
            raise ProtocolError("expected content header after get-ok")
        _, _, body_size, _ = struct.unpack_from(">HHQH", header.payload)
        body = b""
        while len(body) < body_size:
            part = self._recv_frame()
            if part.type != FRAME_BODY:
                raise ProtocolError("expected content body frame")
            body += part.payload
        return delivery_tag, body

    def basic_ack(self, delivery_tag: int, multiple: bool = False) -> None:
        self._send(method_frame(
            self.channel, BASIC_ACK,
            struct.pack(">QB", delivery_tag, 1 if multiple else 0)))

    def queue_bind(self, queue: str, exchange: str, routing_key: str) -> None:
        self._send(method_frame(
            self.channel, QUEUE_BIND,
            struct.pack(">H", 0) + shortstr(queue) + shortstr(exchange)
            + shortstr(routing_key) + b"\x00" + field_table()))
        self._expect(QUEUE_BIND_OK)

    def confirm_select(self) -> None:
        """Publisher confirms (reference: the CDC runner publishes with
        confirms so progress only advances on broker ack)."""
        self._send(method_frame(self.channel, CONFIRM_SELECT, b"\x00"))
        self._expect(CONFIRM_SELECT_OK)
        self.confirm_mode = True

    def publish(self, exchange: str, routing_key: str, body: bytes) -> None:
        self._send(
            method_frame(self.channel, BASIC_PUBLISH,
                         struct.pack(">H", 0) + shortstr(exchange)
                         + shortstr(routing_key) + b"\x00")
            + content_frames(self.channel, body, self.frame_max))
        self.publish_seq += 1
        if self.confirm_mode:
            self.outstanding.add(self.publish_seq)

    def wait_confirms(self) -> None:
        """Block until every published message is acked. Acks may arrive
        out of order and with `multiple` set; a nack is a delivery failure
        the caller must treat as such (the CDC runner keeps its watermark
        in place and re-publishes)."""
        assert self.confirm_mode
        while self.outstanding:
            got = self._recv_frame()
            if got.method not in (BASIC_ACK, BASIC_NACK):
                raise ProtocolError(
                    f"expected basic.ack/nack, got {got.method}")
            self._apply_confirm(got)

    def close(self) -> None:
        try:
            self._send(method_frame(
                0, CONNECTION_CLOSE,
                struct.pack(">H", 200) + shortstr("bye")
                + struct.pack(">HH", 0, 0)))
            self._expect(CONNECTION_CLOSE_OK)
        except Exception:
            pass
        self.sock.close()


# --------------------------------------------------- consumption (testing)

def parse_publishes(raw: bytes) -> Iterator[tuple[str, str, bytes]]:
    """Decode (exchange, routing key, body) triples from a raw channel
    byte stream of publish + content frames — the broker-side half the
    tests use to verify what the client put on the wire."""
    buffer = bytearray(raw)
    pending: Optional[tuple[str, str]] = None
    body_size = 0
    body = b""
    while True:
        got = Frame.parse(buffer)
        if got is None:
            return
        if got.method == BASIC_PUBLISH:
            args = got.args()
            args.u16()
            exchange = args.shortstr()
            routing_key = args.shortstr()
            pending = (exchange, routing_key)
        elif got.type == FRAME_HEADER and pending is not None:
            _, _, body_size, _ = struct.unpack_from(">HHQH", got.payload)
            body = b""
            if body_size == 0:
                yield (*pending, b"")
                pending = None
        elif got.type == FRAME_BODY and pending is not None:
            body += got.payload
            if len(body) >= body_size:
                yield (*pending, body)
                pending = None
