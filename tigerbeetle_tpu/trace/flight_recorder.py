"""Flight recorder: a bounded ring of per-window telemetry for post-mortem.

The device telemetry plane (parallel/partitioned.py TEL_LAYOUT) answers
"what is the fused route doing NOW"; the flight recorder answers "what
was it doing WHEN it died". Each replica keeps the last-N per-window
records — route decision, decoded telemetry summary, epoch digest when
one was verified — and dumps them as a JSON artifact the moment the
serving path quarantines, recovers, or exhausts its retries (the
PartitionedRouter dumps on shard-loss quarantine and resync, the
ServingSupervisor on every recovery cause). Vortex runs harvest the
same artifacts from their replica scratch dirs.

Cross-process merge is LOSSLESS: beside the raw records the recorder
accumulates log2 histograms (trace/histogram.py — the PR 7 merge
property) of the device distributions, so `merge_flight_records` over
N replicas' dumps adds bucket counts exactly; quantiles over the merged
document equal quantiles over the union of samples within the
histogram's ~1% relative error.

Artifact naming: FLIGHT_<pid>_<reason>_<seq>.json under
$TB_TPU_FLIGHT_DIR (default: <tempdir>/tb_tpu_flight). The schema is
documented in docs/operating/monitoring.md alongside the post-mortem
runbook.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
from typing import Optional

from .event import Event
from .histogram import Histogram

# The distributions the recorder accumulates losslessly beside the raw
# ring: fed from each record's telemetry summary when present.
_HIST_KEYS = ("fix_rounds", "exchange_occupancy_pct")


def _flight_dir() -> str:
    return (os.environ.get("TB_TPU_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "tb_tpu_flight"))


class FlightRecorder:
    """Bounded host-side ring of per-window records + dump-on-fault.

    `record()` is cheap (a deque append + optional histogram feeds) and
    runs once per committed window — always-on production posture, the
    Dapper lesson. `dump(reason)` freezes the ring into a JSON artifact
    and counts the `flight_recorder_dump` catalog event; the artifact
    path is returned and kept in `last_dump_path`."""

    def __init__(self, capacity: int = 64, pid: int = 0, tracer=None,
                 out_dir: Optional[str] = None):
        assert capacity > 0
        self.capacity = capacity
        self.pid = pid
        self.tracer = tracer
        self.out_dir = out_dir
        self.seq = 0          # windows recorded, ever
        self.dumps = 0
        self.last_dump_path: Optional[str] = None
        self._ring: collections.deque = collections.deque(
            maxlen=capacity)
        self._hists = {k: Histogram() for k in _HIST_KEYS}

    # ------------------------------------------------------------ recording

    def record(self, *, window: int, route: str, telemetry=None,
               epoch_digest=None, **detail) -> None:
        """Append one per-window record. `telemetry` is the decoded
        summary dict (see PartitionedRouter._absorb_telemetry); its
        `fix_rounds` / `exchange_occupancy_pct` sample lists also feed
        the recorder's mergeable histograms."""
        rec = {"seq": self.seq, "window": int(window),
               "route": str(route)}
        if telemetry is not None:
            rec["telemetry"] = telemetry
            for key in _HIST_KEYS:
                for v in telemetry.get(key) or ():
                    self._hists[key].record(float(v))
        if epoch_digest is not None:
            rec["epoch_digest"] = str(epoch_digest)
        if detail:
            rec["detail"] = detail
        self._ring.append(rec)
        self.seq += 1

    @property
    def records(self) -> list:
        return list(self._ring)

    # --------------------------------------------------------------- dumping

    def to_dict(self) -> dict:
        return {
            "pid": self.pid,
            "capacity": self.capacity,
            "windows_recorded": self.seq,
            "records": self.records,
            "histograms": {k: h.to_dict()
                           for k, h in self._hists.items() if h.count},
        }

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Freeze the ring into FLIGHT_<pid>_<reason>_<seq>.json (or
        `path`) and count flight_recorder_dump tagged with the reason.
        Never raises on I/O: a post-mortem artifact must not turn a
        recovery into a crash — failures land in the returned path
        being '' with the counter still emitted."""
        doc = dict(self.to_dict(), reason=str(reason))
        if path is None:
            d = self.out_dir or _flight_dir()
            try:
                os.makedirs(d, exist_ok=True)
            except OSError:
                d = tempfile.gettempdir()
            path = os.path.join(
                d, f"FLIGHT_{self.pid}_{reason}_{self.seq:06d}.json")
        try:
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError:
            path = ""
        self.dumps += 1
        self.last_dump_path = path or None
        if self.tracer is not None:
            self.tracer.count(Event.flight_recorder_dump,
                              reason=str(reason))
        return path


def merge_flight_records(docs: list) -> dict:
    """Merge N replicas' dump documents (as dicts or file paths) into
    one post-mortem view: records concatenate ordered by (pid, seq),
    histograms ADD losslessly per key (integer bucket counts — the
    PR 7 merge property, so cluster-wide quantiles are exact within
    the histogram error bound)."""
    loaded = []
    for d in docs:
        if isinstance(d, str):
            with open(d) as f:
                d = json.load(f)
        loaded.append(d)
    records = []
    hists: dict = {}
    pids = []
    reasons = []
    for d in loaded:
        pid = d.get("pid", 0)
        pids.append(pid)
        if d.get("reason"):
            reasons.append(d["reason"])
        for r in d.get("records", []):
            records.append(dict(r, pid=pid))
        for k, hd in (d.get("histograms") or {}).items():
            h = Histogram.from_dict(hd)
            if k in hists:
                hists[k].merge(h)
            else:
                hists[k] = h
    records.sort(key=lambda r: (r.get("pid", 0), r.get("seq", 0)))
    return {
        "replicas": sorted(set(pids)),
        "reasons": sorted(set(reasons)),
        "records": records,
        "histograms": {k: h.to_dict() for k, h in hists.items()},
    }
