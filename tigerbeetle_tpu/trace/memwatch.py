"""Device-memory watermark plane: the static-allocation ledger.

The reference's core memory discipline (docs/ARCHITECTURE.md:189-230)
is that serving memory is statically allocated: every resident buffer
is sized by a cap chosen at startup, so the footprint is a FUNCTION OF
CAPS, not of history. This module makes that discipline machine-
checkable (ISSUE 20):

- ``component_bytes(led)`` walks a live DeviceLedger and attributes
  every resident allocation to a named component — the state pytree's
  top-level stores (accounts / transfers / events ring / both hash
  tables / scalars), the double-buffered staged operand pack, the
  harvested device-telemetry block, and the partitioned router's
  per-shard state — bytes computed from shapes and dtypes
  (deterministic on every backend, no allocator introspection needed).
- ``static_ledger(a_cap, t_cap, ...)`` predicts the same components
  from caps alone (it builds the init_state shapes host-side), so the
  prediction can be asserted against measured device bytes
  (tests/test_memory_bounds.py does, on 1/2/8-device meshes).
- ``check_budget(measured, budget)`` compares a measurement against
  the committed ``perf/membudget_r*.json``: any component growing past
  its pinned bytes (beyond the budget's tolerance), any NEW component
  the budget has never heard of, or total growth is a RED — the gate's
  profile leg enforces it with an injected-leak negative.
- ``MemWatch`` emits the watermark as catalog gauges
  (``memory_watermark_bytes`` / ``memory_budget_headroom_bytes``) so
  the footprint flows into StatsD/Prometheus/devhub like any metric,
  and samples per-device allocator stats (``device.memory_stats()``)
  where the backend provides them (TPU does; CPU typically returns
  nothing — the shape-derived ledger is the deterministic source of
  truth everywhere).
"""

from __future__ import annotations

import json
import math
import os
from typing import Optional

from .event import Event

# Worst-case staged-pack accounting: one pipelined window's stacked
# operands at depth W over the largest pad bucket. Kept in sync with
# ops/ledger.py's PAD_BUCKETS tail and the serving pipeline depth.
STAGED_PACK_DEPTH = 2


def leaf_bytes(leaf) -> int:
    """Resident bytes of one array-like leaf (shape x itemsize — works
    for numpy, jax.Array, and ShapeDtypeStruct alike; scalars count
    their dtype width)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def pytree_bytes(tree) -> int:
    """Total resident bytes of a pytree (sum over leaves)."""
    import jax

    return sum(leaf_bytes(x) for x in jax.tree_util.tree_leaves(tree))


def state_component_bytes(state) -> dict:
    """Bytes per top-level store of a ledger state pytree. Nested
    sub-trees are summed under their top key; bare scalar leaves are
    grouped under ``scalars``."""
    out: dict = {}
    scalars = 0
    for key, sub in state.items():
        b = pytree_bytes(sub) if isinstance(sub, dict) else leaf_bytes(sub)
        if isinstance(sub, dict):
            out[f"state.{key}"] = b
        else:
            scalars += b
    out["state.scalars"] = scalars
    return out


def staged_pack_max_bytes(n_pad: int, depth: int = STAGED_PACK_DEPTH,
                          kind: str = "transfer") -> int:
    """Worst-case bytes of one staged window pack: `depth` prepares'
    padded event columns plus their timestamp/count lanes. Measured
    from a real padded-event dict (the exact columns the stager device-
    puts), not a hand-kept formula."""
    from ..ops.batch import transfers_to_arrays
    from ..ops.ledger import pad_transfer_events
    from ..types import Transfer

    ev = pad_transfer_events(transfers_to_arrays(
        [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                  amount=1, ledger=1, code=1)]), n_pad)
    per_prepare = pytree_bytes(ev)
    # + one u64 timestamp and one i32 count lane per prepare.
    return depth * (per_prepare + 8 + 4)


def telemetry_block_bytes(n_shards: int, depth: int) -> int:
    """The harvested [n_shards, W, TEL_WORDS] u32 device-telemetry
    block of one fused partitioned-chain window."""
    from ..parallel.partitioned import TEL_WORDS

    return n_shards * depth * TEL_WORDS * 4


def static_ledger(a_cap: int, t_cap: int, *, n_shards: int = 1,
                  window_depth: int = 8, n_pad: Optional[int] = None,
                  orphan_cap: Optional[int] = None,
                  e_cap: Optional[int] = None) -> dict:
    """The deterministic static-allocation ledger: predicted resident
    bytes per component from caps alone. For a partitioned mesh the
    per-shard caps divide by n_shards (matching PartitionedRouter /
    jaxhound.registry fixtures) and components are GLOBAL (x n_shards);
    ``per_device_bytes`` is the ~1/n per-shard share."""
    from ..ops.ledger import N_PAD, init_state

    if n_pad is None:
        n_pad = N_PAD
    if n_shards > 1:
        sub = init_state(a_cap // n_shards, t_cap // n_shards,
                         orphan_cap=(orphan_cap or (1 << 16)) // n_shards,
                         e_cap=None if e_cap is None else e_cap // n_shards)
        comps = {k: v * n_shards
                 for k, v in state_component_bytes(sub).items()}
    else:
        comps = state_component_bytes(init_state(
            a_cap, t_cap, orphan_cap=orphan_cap, e_cap=e_cap))
    comps["staged_pack"] = staged_pack_max_bytes(n_pad)
    comps["telemetry_block"] = telemetry_block_bytes(
        n_shards, window_depth) if n_shards > 1 else 0
    total = sum(comps.values())
    return {
        "caps": {"a_cap": a_cap, "t_cap": t_cap, "n_shards": n_shards,
                 "window_depth": window_depth, "n_pad": n_pad},
        "components": comps,
        "total_bytes": total,
        "per_device_bytes": total // max(1, n_shards),
    }


def measure_ledger(led) -> dict:
    """The LIVE counterpart of static_ledger: component bytes measured
    from a DeviceLedger's actual resident pytrees (state, any staged
    pack in flight, the partitioned router's sharded state + telemetry
    block). Shape-derived, so it is exact and deterministic — the
    watermark can never wobble with allocator internals."""
    comps = state_component_bytes(led.state)
    staged = getattr(led, "_staged", None)
    staged_b = 0
    if staged is not None:
        fut = staged[-1]
        if fut.done() and not fut.cancelled():
            try:
                payload, _ = fut.result()
                staged_b = pytree_bytes(payload)
            except Exception:
                staged_b = 0
    comps["staged_pack"] = staged_b
    router = getattr(led, "_part_router", None)
    n_shards = 1
    if router is not None:
        n_shards = router.n_shards
        pstate = getattr(led, "_part_state", None)
        if pstate is not None:
            comps["partitioned_state"] = pytree_bytes(pstate)
        comps["telemetry_block"] = telemetry_block_bytes(
            n_shards, STAGED_PACK_DEPTH)
    total = sum(comps.values())
    return {"components": comps, "total_bytes": total,
            "per_device_bytes": total // max(1, n_shards),
            "n_shards": n_shards}


def device_memory_stats() -> list:
    """Per-device allocator stats where the backend provides them
    (``bytes_in_use`` / ``peak_bytes_in_use`` on TPU/GPU). Returns one
    dict per device; ``stats`` is None where unsupported (CPU) — the
    static ledger is the watermark source of truth there."""
    import jax

    out = []
    for d in jax.devices():
        stats = None
        try:
            s = d.memory_stats()
            if s:
                stats = {k: int(v) for k, v in s.items()
                         if isinstance(v, (int, float))
                         and k in ("bytes_in_use", "peak_bytes_in_use",
                                   "bytes_limit", "largest_alloc_size")}
        except Exception:
            stats = None
        out.append({"device": str(d), "platform": d.platform,
                    "stats": stats})
    return out


def check_budget(measured: dict, budget: dict) -> list:
    """Budget audit: measured components vs the committed membudget.
    REDs on (a) any component past its pinned bytes beyond tolerance,
    (b) any component the budget never pinned (a leak shows up as a
    new allocation before it shows up as growth), (c) total growth.
    Returns human-readable RED lines (empty = green)."""
    tol = float(budget.get("tolerance", 0.02))
    pinned = budget.get("components", {})
    reds = []
    for comp, got in sorted(measured["components"].items()):
        limit = pinned.get(comp)
        if limit is None:
            if got:
                reds.append(
                    f"memwatch RED: component {comp!r} ({got} bytes) is "
                    f"not in the committed budget (new allocation — "
                    f"re-pin perf/membudget with --write if intended)")
            continue
        if got > math.ceil(limit * (1.0 + tol)):
            reds.append(
                f"memwatch RED: component {comp!r} grew to {got} bytes "
                f"vs pinned {limit} (tolerance {tol:.0%})")
    total, limit = measured["total_bytes"], budget.get("total_bytes")
    if limit is not None and total > math.ceil(limit * (1.0 + tol)):
        reds.append(
            f"memwatch RED: total watermark {total} bytes vs pinned "
            f"{limit} (tolerance {tol:.0%})")
    return reds


def load_budget(path: Optional[str] = None) -> dict:
    """The committed membudget (newest perf/membudget_r*.json)."""
    if path is None:
        from ..jaxhound import newest_membudget_path

        path = newest_membudget_path()
    with open(path) as f:
        budget = json.load(f)
    for key in ("components", "total_bytes"):
        if key not in budget:
            raise ValueError(
                f"membudget {os.path.basename(str(path))} is missing "
                f"{key!r} — not a valid static-allocation budget")
    return budget


class MemWatch:
    """The watermark sampler the serving supervisor ticks: measures the
    static-allocation ledger, emits the catalog gauges, and keeps the
    last observation (+ budget verdict) for ``stats()``/devhub."""

    def __init__(self, tracer=None, budget_path: Optional[str] = None,
                 budget: Optional[dict] = None):
        from .tracer import NullTracer

        self.tracer = tracer if tracer is not None else NullTracer()
        self._budget_path = budget_path
        self._budget = budget
        self.observations = 0
        self.last: Optional[dict] = None
        self.reds: list = []

    @property
    def budget(self) -> Optional[dict]:
        if self._budget is None:
            try:
                self._budget = load_budget(self._budget_path)
            except (OSError, ValueError):
                self._budget = None
        return self._budget

    def observe(self, led, with_device_stats: bool = False) -> dict:
        """One watermark sample: measure, gauge, audit. Cheap (a pytree
        walk over shapes), so the supervisor ticks it at every epoch
        verification."""
        rec = measure_ledger(led)
        self.observations += 1
        self.tracer.gauge(Event.memory_watermark_bytes,
                          rec["total_bytes"])
        budget = self.budget
        if budget is not None:
            rec["budget_total_bytes"] = budget["total_bytes"]
            rec["headroom_bytes"] = (budget["total_bytes"]
                                     - rec["total_bytes"])
            self.tracer.gauge(Event.memory_budget_headroom_bytes,
                              rec["headroom_bytes"])
            self.reds = check_budget(rec, budget)
            rec["budget_ok"] = not self.reds
        if with_device_stats:
            rec["device_memory_stats"] = device_memory_stats()
        self.last = rec
        return rec

    def stats(self) -> dict:
        return {"observations": self.observations,
                "last": self.last, "reds": list(self.reds)}
