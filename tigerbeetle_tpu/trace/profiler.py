"""Sampled per-dispatch profiling + the static roofline cost model.

The trace plane (spans, device telemetry, request trees) says how long
a commit window took; this module says where the DEVICE time goes and
how far each dispatch tier sits from what the hardware could do
(ISSUE 20, the attribution side of the 302k -> 10M tps campaign):

- ``DispatchProfiler`` wraps the serving dispatch thunks (chain /
  partitioned-chain / per-batch) with deterministic 1-in-N sampling.
  A sampled dispatch is timed wall-to-ready — ``block_until_ready`` on
  the dispatch result, so the timer covers real device execution, not
  just async enqueue — and lands in the ``dispatch_device_time``
  catalog histogram partitioned by route and shape tier. Unsampled
  dispatches pay one integer increment (the ##profile bench record
  proves the whole plane ≤ the 1.05 overhead ceiling in
  perf/membudget_r*.json).
- Where the backend supports programmatic capture, ``capture_once``
  wraps one sampled dispatch in a ``jax.profiler`` trace (a real XLA
  profile artifact under ``capture_dir``); elsewhere the deterministic
  timer fallback is the whole story and the capture records why.
- ``static_cost_model`` derives FLOPs + HBM bytes per serving entry
  from the lowered HLO via the jaxhound registry (compiled
  ``cost_analysis``), and ``roofline_fractions`` divides each tier's
  achievable time (max of compute-limit and bandwidth-limit against
  nominal platform peaks) by its MEASURED sampled dispatch time — the
  achieved-vs-roofline fraction every bench record now carries.

Nothing here runs device code of its own: the profiler observes the
real serving routes in situ (reference: src/trace.zig's discipline —
profiling is a property of the serving path, not a separate harness).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .event import Event

# Nominal peak envelopes per platform: (FLOP/s, HBM bytes/s). These are
# headline device numbers, not measured ceilings — the roofline fraction
# is an attribution signal (which tier is furthest from achievable),
# not a benchmark claim. v5e: 197 TFLOP/s bf16, 819 GB/s HBM. The cpu
# row is a deliberately round envelope so fractions stay comparable
# across dev runs; on-chip campaigns read the tpu row.
NOMINAL_PEAKS = {
    "tpu": (197e12, 819e9),
    "gpu": (60e12, 1000e9),
    "cpu": (100e9, 50e9),
}

# Representative registry entry per dispatch tier (jaxhound.registry
# names): the cost model lowers these, not all 19 entries — one per
# route keeps the bench probe seconds, not minutes.
TIER_ENTRIES = {
    "flat": "create_transfers_fast_jit",
    "chain": "create_transfers_chain_jit",
    "partitioned_chain": "partitioned_chain_step",
}

# The serving ledger's route names for each registry route: the live
# dispatch labels windows "per_batch" where the registry's flat tier
# serves them (same jit entries, different vocabulary layer).
ROUTE_ALIASES = {
    "flat": ("flat", "per_batch"),
    "chain": ("chain",),
    "partitioned_chain": ("partitioned_chain",),
}


class DispatchProfiler:
    """Deterministic 1-in-N dispatch sampler feeding the
    ``dispatch_device_time`` histogram.

    ``time(thunk, route=..., tier=...)`` replaces a bare ``thunk()``
    at the dispatch site. Sampling is a modular counter (no RNG — the
    serving path stays deterministic-replay clean); a sampled call is
    timed through ``jax.block_until_ready`` on its result. The result
    is returned either way, so the call site is oblivious."""

    def __init__(self, tracer=None, sample_every: int = 8,
                 capture_dir: Optional[str] = None):
        from .tracer import NullTracer

        if sample_every < 1:
            raise ValueError(f"sample_every must be >= 1, "
                             f"got {sample_every}")
        self.tracer = tracer if tracer is not None else NullTracer()
        self.sample_every = sample_every
        self.capture_dir = capture_dir
        self.dispatches = 0
        self.samples = 0
        self.last_us: Optional[float] = None
        # One-shot programmatic capture state: armed by capture_once(),
        # consumed by the next sampled dispatch.
        self._capture_armed = False
        self.capture_result: Optional[dict] = None

    def capture_once(self, capture_dir: Optional[str] = None) -> None:
        """Arm a one-shot ``jax.profiler`` trace around the next
        sampled dispatch. The artifact (or the reason the backend
        refused) lands in ``capture_result``."""
        if capture_dir is not None:
            self.capture_dir = capture_dir
        self._capture_armed = True

    def time(self, thunk: Callable[[], object], *, route, tier):
        """Run one dispatch, sampled 1-in-N. Returns the thunk's
        result unchanged. `route`/`tier` may be strings or zero-arg
        callables — callables resolve AFTER the thunk runs, because the
        serving ledger only knows which route a window took once it has
        dispatched it (the same late-tagging the window_commit span
        does)."""
        self.dispatches += 1
        if (self.dispatches - 1) % self.sample_every:
            return thunk()
        import jax

        capture = self._capture_armed
        if capture:
            self._capture_armed = False
            self._start_capture()
        t0 = time.perf_counter_ns()
        try:
            out = thunk()
            jax.block_until_ready(out)
        finally:
            if capture:
                self._stop_capture()
        dt_us = (time.perf_counter_ns() - t0) / 1e3
        self.samples += 1
        self.last_us = dt_us
        self.tracer.observe(Event.dispatch_device_time, dt_us,
                            route=str(route() if callable(route)
                                      else route),
                            tier=str(tier() if callable(tier)
                                     else tier))
        return out

    def _start_capture(self) -> None:
        import jax

        if self.capture_dir is None:
            self.capture_result = {"ok": False,
                                   "reason": "no capture_dir set"}
            return
        try:
            jax.profiler.start_trace(self.capture_dir)
            self.capture_result = {"ok": True, "dir": self.capture_dir}
        except Exception as e:  # backend/platform-dependent support
            self.capture_result = {
                "ok": False,
                "reason": f"{type(e).__name__}: {e} "
                          f"(deterministic timer fallback in effect)"}

    def _stop_capture(self) -> None:
        if not (self.capture_result and self.capture_result.get("ok")):
            return
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self.capture_result = {"ok": False,
                                   "reason": f"stop_trace: "
                                             f"{type(e).__name__}: {e}"}

    def stats(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "samples": self.samples,
            "sample_every": self.sample_every,
            "last_us": self.last_us,
            "capture": self.capture_result,
        }


# ------------------------------------------------------ static cost model


def static_cost_model(include_partitioned: Optional[bool] = None,
                      depth: int = 4) -> dict:
    """FLOPs + HBM bytes per dispatch tier from the lowered HLO.

    Lowers one representative jaxhound registry entry per route at the
    representative window depth, runs the compiled artifact's
    ``cost_analysis`` (jaxhound.analyze_lowered — failures are recorded
    as ``stats_unavailable`` strings, never swallowed as zero cost),
    and attaches the nominal-peak roofline seconds per platform. The
    result is deterministic for a given jax version + device count, so
    bench records can diff it across rounds."""
    import jax

    from ..jaxhound import analyze_lowered
    from ..jaxhound.registry import entries

    platform = jax.devices()[0].platform
    reg = entries(include_partitioned=include_partitioned)
    model: dict = {"platform": platform, "depth": depth, "tiers": {}}
    for tier, entry_name in TIER_ENTRIES.items():
        entry = reg.get(entry_name)
        if entry is None:  # partitioned tier absent on small meshes
            continue
        try:
            analysis = analyze_lowered(entry.lower(depth=depth))
        except Exception as e:
            model["tiers"][tier] = {
                "entry": entry_name,
                "unavailable": f"{type(e).__name__}: {e}"}
            continue
        stats = analysis.get("stats", {})
        row = {
            "entry": entry_name,
            "route": entry.route,
            "instructions": analysis.get("instructions"),
            "flops": stats.get("flops"),
            "hbm_bytes": stats.get("bytes accessed"),
            "optimal_seconds": stats.get("optimal_seconds"),
        }
        if analysis.get("stats_unavailable"):
            row["stats_unavailable"] = analysis["stats_unavailable"]
        rs = roofline_seconds(row["flops"], row["hbm_bytes"], platform)
        if rs is not None:
            row["roofline_seconds"] = rs
        model["tiers"][tier] = row
    return model


def roofline_seconds(flops, hbm_bytes, platform: str) -> Optional[float]:
    """Achievable seconds for one dispatch under the nominal peaks:
    max of the compute limit and the bandwidth limit (classic roofline
    — whichever wall binds). None when the cost analysis gave nothing
    (never fabricate a 0-second roofline)."""
    peaks = NOMINAL_PEAKS.get(platform)
    if peaks is None or not flops and not hbm_bytes:
        return None
    peak_flops, peak_bw = peaks
    return max((flops or 0.0) / peak_flops,
               (hbm_bytes or 0.0) / peak_bw)


def measured_dispatch_us(tracer) -> dict:
    """Per-series sampled dispatch summaries from a recording tracer:
    series key -> {route, tier, count, p50_us, p99_us, max_us}. Series
    keys follow the tracer's hist_tags projection
    (``dispatch_device_time|route:...,tier:...``)."""
    out: dict = {}
    series = getattr(tracer, "histogram_series", None)
    if not series:
        return out
    for key, (name, tags) in series.items():
        if name != Event.dispatch_device_time.name:
            continue
        h = tracer.histograms[key]
        s = h.summary()
        out[key] = {
            "route": tags.get("route"),
            "tier": tags.get("tier"),
            "count": s.get("count"),
            "p50_us": h.quantile(0.5),
            "p99_us": h.quantile(0.99),
            "max_us": s.get("max"),
        }
    return out


def roofline_fractions(cost_model: dict, measured: dict) -> dict:
    """Achieved-vs-roofline fraction per tier: roofline seconds over
    the measured sampled-dispatch p50 (1.0 = at the nominal wall;
    0.01 = two orders of magnitude of attribution left to claim).
    ``measured`` is ``measured_dispatch_us``'s output; routes are
    matched tier->route via the cost model rows."""
    out: dict = {}
    for tier, row in cost_model.get("tiers", {}).items():
        rs = row.get("roofline_seconds")
        if rs is None:
            continue
        route = row.get("route")
        accepted = ROUTE_ALIASES.get(route, (route,))
        p50s = [m["p50_us"] for m in measured.values()
                if m.get("route") in accepted and m.get("count")]
        if not p50s:
            continue
        measured_s = min(p50s) / 1e6  # best tier sample: the fastest
        if measured_s <= 0:
            continue
        out[tier] = {
            "route": route,
            "roofline_seconds": rs,
            "measured_p50_s": measured_s,
            "fraction": rs / measured_s,
        }
    return out


def profile_probe(tracer=None, profiler: Optional[DispatchProfiler] = None,
                  include_partitioned: Optional[bool] = None,
                  depth: int = 4) -> dict:
    """The bench ``##profile`` record: static cost model + measured
    sampled-dispatch histograms + achieved-vs-roofline fractions per
    tier + profiler/sampling counters. Pure assembly over state the
    run already produced — the probe itself dispatches nothing."""
    cost_model = static_cost_model(
        include_partitioned=include_partitioned, depth=depth)
    measured = measured_dispatch_us(tracer) if tracer is not None else {}
    out = {
        "cost_model": cost_model,
        "dispatch_device_time": measured,
        "roofline": roofline_fractions(cost_model, measured),
    }
    if profiler is not None:
        out["sampler"] = profiler.stats()
    return out
