"""Tracers: the no-op production default and the recording tracer.

reference: src/trace.zig — span start/stop compiled into the hot path,
Chrome/Perfetto JSON via --trace, StatsD aggregation via trace/statsd.zig.
The tracer is injected at construction (replica, journal, scrubber,
message bus, serving supervisor, sharded router); the default NullTracer
keeps every hot path free of overhead (bench.py's ##trace probe records
that cost every run).

The recording `Tracer` enforces the typed catalog (trace/event.py): a
span/counter/gauge outside the catalog, or a tag key outside the event's
schema, is a hard error. Spans land in a bounded ring; eviction is
SELF-DESCRIBING (a dropped_events counter plus an instant marker event,
so a truncated Chrome trace says so instead of silently starting late).

Cross-process alignment: span timestamps are wall-clock anchored — the
tracer records `time.time_ns() - perf_counter_ns()` once at construction
and bakes the offset into every emitted `ts`, so per-replica traces from
different processes merge onto one timeline (trace/merge.py) without any
post-hoc clock guessing.
"""

from __future__ import annotations

import json
import time as _time
from typing import Optional

from .event import TID_BASE, Event, EventKind, lookup
from .histogram import Histogram
from .statsd import StatsD, TimingAggregates


class NullTracer:
    """No-op tracer (production default unless --trace/--statsd is set).
    Accepts anything: enforcement is the recording tracer's job — the
    null path must stay a handful of attribute lookups."""

    def span(self, event, **tags):
        return _NULL_SPAN

    def begin(self, event, **tags) -> None:
        pass

    def end(self, event, **tags) -> None:
        pass

    def count(self, event, value: int = 1, **tags) -> None:
        pass

    def gauge(self, event, value: float, **tags) -> None:
        pass

    def observe(self, event, value: float, **tags) -> None:
        pass

    def dump_chrome_trace(self, path: str) -> None:
        pass

    def flush_statsd(self) -> None:
        pass


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def tags(self) -> dict:
        return {}  # a throwaway: late-tagging a null span is a no-op


_NULL_SPAN = _NullSpan()


class Tracer(NullTracer):
    """Recording tracer: bounded ring of completed spans, counters,
    gauges, per-event timing aggregates, and the emitted-name set the
    gate's coverage leg audits."""

    def __init__(self, capacity: int = 65536,
                 statsd: Optional[StatsD] = None, pid: int = 0,
                 emit_interval_s: float = 10.0):
        self.capacity = capacity
        self.statsd = statsd
        self.pid = pid
        self.emit_interval_s = emit_interval_s
        self.events: list[dict] = []
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.dropped_events = 0
        # Catalog coverage record: every event name this tracer emitted.
        self.emitted: set[str] = set()
        # Wall-clock anchor: perf_counter_ns + _epoch_ns == time_ns, so
        # emitted ts values are comparable ACROSS processes.
        self._epoch_ns = _time.time_ns() - _time.perf_counter_ns()
        self.aggregates = TimingAggregates()
        # CUMULATIVE distributions for the Prometheus exposition and
        # the merged-trace metadata: series key -> Histogram, fed at
        # span close BEFORE any ring bookkeeping (ring eviction drops
        # span *events*; it must never dent a distribution) and by
        # observe() for histogram-kind events. Unlike `aggregates`
        # (flush-and-reset, StatsD interval semantics) these only grow.
        self.histograms: dict[str, Histogram] = {}
        # series key -> (event name, partition tags) for exposition.
        self.histogram_series: dict[str, tuple] = {}
        self._last_flush_ns = _time.perf_counter_ns()
        # Concurrency lanes: event name -> busy slot set (sync spans),
        # and event name -> {slot: (start_ns, tags)} (begin/end spans).
        self._busy: dict[str, set] = {}
        self._open: dict[str, dict] = {}
        self._lanes_used: dict[int, str] = {}

    # ------------------------------------------------------------ catalog

    def _check(self, event, kind: EventKind, tags: dict) -> Event:
        ev = lookup(event)
        if ev.kind is not kind:
            raise ValueError(
                f"trace event {ev.name} is a {ev.kind.value}, used as a "
                f"{kind.value}")
        if tags and not set(tags) <= set(ev.tags):
            raise ValueError(
                f"trace event {ev.name}: tags {sorted(set(tags) - set(ev.tags))} "
                f"are outside its schema {ev.tags}")
        return ev

    def _lane(self, ev: Event) -> int:
        busy = self._busy.setdefault(ev.name, set())
        slot = next((s for s in range(ev.slots) if s not in busy),
                    ev.slots - 1)  # saturated: share the last lane
        busy.add(slot)
        tid = TID_BASE[ev] + slot
        self._lanes_used.setdefault(tid, f"{ev.name}[{slot}]")
        return slot

    # -------------------------------------------------------------- spans

    def span(self, event, **tags):
        ev = self._check(event, EventKind.span, tags)
        return _Span(self, ev, tags)

    def begin(self, event, **tags) -> None:
        """Open a multi-tick phase span (view change, state sync,
        rebuild). A begin while the event is already open (same slot
        semantics as overlapping sync spans) first closes the open one."""
        ev = self._check(event, EventKind.span, tags)
        open_ = self._open.setdefault(ev.name, {})
        if len(open_) >= ev.slots:
            self.end(ev)  # saturated: close the oldest occurrence
        slot = self._lane(ev)
        open_[slot] = (_time.perf_counter_ns(), tags)

    def end(self, event, **tags) -> None:
        """Close the oldest open occurrence of a begin() span; a no-op
        when none is open (phases may end from several call sites)."""
        ev = self._check(event, EventKind.span, tags)
        open_ = self._open.get(ev.name)
        if not open_:
            return
        slot = min(open_)
        start_ns, begin_tags = open_.pop(slot)
        self._busy[ev.name].discard(slot)
        merged = dict(begin_tags, **tags)
        self._record(ev, start_ns, _time.perf_counter_ns() - start_ns,
                     merged, TID_BASE[ev] + slot)

    # --------------------------------------------------- counters / gauges

    def count(self, event, value: int = 1, **tags) -> None:
        ev = self._check(event, EventKind.counter, tags)
        self.emitted.add(ev.name)
        self.counters[ev.name] = self.counters.get(ev.name, 0) + value
        if self.statsd is not None:
            self.statsd.count(ev.name, value, **tags)
            self._maybe_flush()

    def gauge(self, event, value: float, **tags) -> None:
        ev = self._check(event, EventKind.gauge, tags)
        self.emitted.add(ev.name)
        self.gauges[ev.name] = value
        if self.statsd is not None:
            self.statsd.gauge(ev.name, value, **tags)
            self._maybe_flush()

    # ---------------------------------------------------------- histograms

    def observe(self, event, value: float, **tags) -> None:
        """Record one sample of a histogram-kind event (unit: whatever
        the event's doc declares). Span durations need no observe() —
        every span feeds its event's histogram at close."""
        ev = self._check(event, EventKind.histogram, tags)
        self.emitted.add(ev.name)
        self._histogram(ev, tags).record(value)
        self.aggregates.record(ev.name, float(value),
                               self._hist_tags(ev, tags))
        if self.statsd is not None:
            self._maybe_flush()

    def _hist_tags(self, ev: Event, tags: dict) -> dict:
        if not ev.hist_tags or not tags:
            return {}
        return {k: tags[k] for k in ev.hist_tags if k in tags}

    def _histogram(self, ev: Event, tags: dict) -> Histogram:
        ht = self._hist_tags(ev, tags)
        key = ev.name if not ht else ev.name + "|" + ",".join(
            f"{k}:{v}" for k, v in sorted(ht.items()))
        h = self.histograms.get(key)
        if h is None:
            h = self.histograms[key] = Histogram()
            self.histogram_series[key] = (ev.name, ht)
        return h

    # ----------------------------------------------------------- recording

    def _record(self, ev: Event, start_ns: int, dur_ns: int,
                tags: dict, tid: int) -> None:
        self.emitted.add(ev.name)
        # Distributions first, ring second: accumulation at span close
        # must be complete BEFORE eviction can touch the span events,
        # so a halved ring never dents a histogram or an aggregate.
        dur_us = dur_ns / 1000.0
        self._histogram(ev, tags).record(dur_us)
        self.aggregates.record(ev.name, dur_us, self._hist_tags(ev, tags))
        if len(self.events) >= self.capacity:
            dropped = self.capacity // 2
            del self.events[:dropped]
            self.dropped_events += dropped
            # Self-describing truncation (satellite: a halved ring must
            # say so): a counter plus an instant marker INSIDE the trace.
            self.count(Event.trace_dropped_events, dropped)
            self.events.append({
                "name": Event.trace_dropped_events.name, "ph": "i",
                "ts": (start_ns + self._epoch_ns) / 1000.0,
                "pid": self.pid, "tid": 0, "s": "p",
                "args": {"dropped_total": self.dropped_events},
            })
        self.events.append({
            "name": ev.name, "ph": "X",
            "ts": (start_ns + self._epoch_ns) / 1000.0,
            "dur": dur_us,
            "pid": self.pid, "tid": tid, "args": tags,
        })
        if self.statsd is not None:
            self._maybe_flush()

    def _maybe_flush(self) -> None:
        now = _time.perf_counter_ns()
        if now - self._last_flush_ns >= self.emit_interval_s * 1e9:
            self._last_flush_ns = now
            self.aggregates.flush_to(self.statsd)

    def flush_statsd(self) -> None:
        """Force-flush the timing aggregates (shutdown path)."""
        if self.statsd is not None:
            self._last_flush_ns = _time.perf_counter_ns()
            self.aggregates.flush_to(self.statsd)

    # --------------------------------------------------------------- dump

    def chrome_dict(self) -> dict:
        """Chrome/Perfetto-loadable document with process/thread names
        and the metadata block trace/merge.py keys on."""
        meta_events = [{
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": f"replica {self.pid}"},
        }]
        for tid in sorted(self._lanes_used):
            meta_events.append({
                "name": "thread_name", "ph": "M", "pid": self.pid,
                "tid": tid, "args": {"name": self._lanes_used[tid]},
            })
        return {
            "traceEvents": meta_events + self.events,
            "metadata": {
                "pid": self.pid,
                "clock_anchor_ns": self._epoch_ns,
                "dropped_events": self.dropped_events,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "aggregates": self.aggregates.snapshot(),
                # Cumulative per-series distributions: losslessly
                # mergeable across replica documents (trace/merge.py
                # adds bucket counts), eviction-proof unlike the ring.
                "histograms": {
                    key: {"event": self.histogram_series[key][0],
                          "tags": dict(self.histogram_series[key][1]),
                          **h.to_dict()}
                    for key, h in self.histograms.items()},
            },
        }

    def dump_chrome_trace(self, path: str) -> None:
        """Chrome/Perfetto-loadable trace (reference: --trace=file)."""
        with open(path, "w") as f:
            json.dump(self.chrome_dict(), f)


class _Span:
    __slots__ = ("tracer", "event", "tags", "start", "slot")

    def __init__(self, tracer: Tracer, event: Event, tags: dict):
        self.tracer = tracer
        self.event = event
        self.tags = tags

    def __enter__(self):
        self.slot = self.tracer._lane(self.event)
        self.start = _time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = _time.perf_counter_ns() - self.start
        self.tracer._busy[self.event.name].discard(self.slot)
        self.tracer._record(self.event, self.start, dur, self.tags,
                            TID_BASE[self.event] + self.slot)
        return False
