"""Tracers: the no-op production default and the recording tracer.

reference: src/trace.zig — span start/stop compiled into the hot path,
Chrome/Perfetto JSON via --trace, StatsD aggregation via trace/statsd.zig.
The tracer is injected at construction (replica, journal, scrubber,
message bus, serving supervisor, sharded router); the default NullTracer
keeps every hot path free of overhead (bench.py's ##trace probe records
that cost every run).

The recording `Tracer` enforces the typed catalog (trace/event.py): a
span/counter/gauge outside the catalog, or a tag key outside the event's
schema, is a hard error. Spans land in a bounded ring; eviction is
SELF-DESCRIBING (a dropped_events counter plus an instant marker event,
so a truncated Chrome trace says so instead of silently starting late).

Cross-process alignment: span timestamps are wall-clock anchored — the
tracer records `time.time_ns() - perf_counter_ns()` once at construction
and bakes the offset into every emitted `ts`, so per-replica traces from
different processes merge onto one timeline (trace/merge.py) without any
post-hoc clock guessing.
"""

from __future__ import annotations

import json
import time as _time
from typing import Optional

from .context import TraceContext, fmt_span_id, fmt_trace_id
from .event import TID_BASE, Event, EventKind, lookup
from .histogram import Histogram
from .statsd import StatsD, TimingAggregates

# The recording span path reads per-event constants through `ev._hot`
# (trace/event.py): one plain attribute access instead of enum property
# hops or member-keyed dict lookups (Enum.__hash__ is Python-level).
# The traced-vs-NullTracer overhead ratios in the bench ##trace record
# guard this path.


class NullTracer:
    """No-op tracer (production default unless --trace/--statsd is set).
    Accepts anything: enforcement is the recording tracer's job — the
    null path must stay a handful of attribute lookups."""

    def span(self, event, ctx=None, **tags):
        return _NULL_SPAN

    def begin(self, event, **tags) -> None:
        pass

    def end(self, event, **tags) -> None:
        pass

    def count(self, event, value: int = 1, **tags) -> None:
        pass

    def gauge(self, event, value: float, **tags) -> None:
        pass

    def observe(self, event, value: float, **tags) -> None:
        pass

    def now_ns(self) -> int:
        """Timestamp for record_span(); 0 on the null path so traced
        call sites never touch a clock when tracing is off."""
        return 0

    def record_span(self, event, start_ns: int, dur_ns: int, *,
                    ctx=None, span_id: int = 0, links=(), **tags) -> None:
        pass

    def mint_span_id(self) -> int:
        return 0

    def keep_trace(self, trace_id, reason: str) -> None:
        pass

    def dump_chrome_trace(self, path: str) -> None:
        pass

    def flush_statsd(self) -> None:
        pass


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def tags(self) -> dict:
        return {}  # a throwaway: late-tagging a null span is a no-op

    @property
    def ctx(self):
        return None  # no causal identity on the null path

    def link(self, trace_id) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer(NullTracer):
    """Recording tracer: bounded ring of completed spans, counters,
    gauges, per-event timing aggregates, and the emitted-name set the
    gate's coverage leg audits."""

    def __init__(self, capacity: int = 65536,
                 statsd: Optional[StatsD] = None, pid: int = 0,
                 emit_interval_s: float = 10.0):
        self.capacity = capacity
        self.statsd = statsd
        self.pid = pid
        self.emit_interval_s = emit_interval_s
        self.events: list[dict] = []
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.dropped_events = 0
        # Catalog coverage record: every event name this tracer emitted.
        self.emitted: set[str] = set()
        # Wall-clock anchor: perf_counter_ns + _epoch_ns == time_ns, so
        # emitted ts values are comparable ACROSS processes.
        self._epoch_ns = _time.time_ns() - _time.perf_counter_ns()
        # No StatsD -> the aggregates' per-interval percentile
        # histograms would never be flushed; skip feeding them. The
        # span-close path updates `_agg` directly on that (bench) path;
        # the alias dodges two attribute hops per span.
        self.aggregates = TimingAggregates(with_hist=statsd is not None)
        self._agg = self.aggregates._agg
        # CUMULATIVE distributions for the Prometheus exposition and
        # the merged-trace metadata: series key -> Histogram, fed at
        # span close BEFORE any ring bookkeeping (ring eviction drops
        # span *events*; it must never dent a distribution) and by
        # observe() for histogram-kind events. Unlike `aggregates`
        # (flush-and-reset, StatsD interval semantics) these only grow.
        self.histograms: dict[str, Histogram] = {}
        # series key -> (event name, partition tags) for exposition.
        self.histogram_series: dict[str, tuple] = {}
        self._last_flush_ns = _time.perf_counter_ns()
        # Concurrency lanes: event name -> busy slot set (sync spans),
        # and event name -> {slot: (start_ns, tags)} (begin/end spans).
        self._busy: dict[str, set] = {}
        self._open: dict[str, dict] = {}
        self._lanes_used: dict[int, str] = {}
        # Causal tracing (ISSUE 15): pid-salted monotonic span ids (no
        # randomness in the deterministic core), the tail-retention set
        # (trace_id hex -> keep reason), and per-series exemplars (last
        # traced sample: the Prometheus exposition links a latency
        # bucket to a concrete kept trace).
        self._span_seq = 0
        self.kept_traces: dict[str, str] = {}
        self.exemplars: dict[str, dict] = {}

    # ------------------------------------------------------------ catalog

    def _check(self, event, kind: EventKind, tags: dict) -> Event:
        ev = event if event.__class__ is Event else lookup(event)
        hot = ev._hot
        if hot[1] is not kind:
            raise ValueError(
                f"trace event {ev.name} is a {ev.kind.value}, used as a "
                f"{kind.value}")
        if tags and not set(tags) <= hot[2]:
            raise ValueError(
                f"trace event {ev.name}: tags {sorted(set(tags) - set(ev.tags))} "
                f"are outside its schema {ev.tags}")
        return ev

    def _lane(self, ev: Event) -> int:
        name, _, _, slots, _, tid0 = ev._hot
        busy = self._busy.get(name)
        if busy is None:
            busy = self._busy[name] = set()
        slot = 0 if not busy else next(
            (s for s in range(slots) if s not in busy),
            slots - 1)  # saturated: share the last lane
        busy.add(slot)
        tid = tid0 + slot
        if tid not in self._lanes_used:
            self._lanes_used[tid] = f"{name}[{slot}]"
        return slot

    # -------------------------------------------------------------- spans

    def span(self, event, ctx: Optional[TraceContext] = None, **tags):
        """Open a sync span.  With `ctx` the span joins that request's
        causal tree: it mints a pid-salted span id, records trace_id/
        span_id/parent_id into its args (AFTER schema check — causal
        keys are reserved, not per-event schema), and exposes `.ctx`,
        the child context to propagate onward."""
        ev = self._check(event, EventKind.span, tags)
        return _Span(self, ev, tags, ctx)

    def mint_span_id(self) -> int:
        """Pid-salted monotonic span id (unique across the cluster as
        long as pids are; never 0 — 0 means 'root, no parent')."""
        self._span_seq += 1
        return ((self.pid & 0xFFFF) << 48) | self._span_seq

    def now_ns(self) -> int:
        """Monotonic timestamp in record_span()'s domain.  Call sites
        in the deterministic core use this instead of touching a clock
        directly (the null tracer returns 0 and records nothing)."""
        return _time.perf_counter_ns()

    def record_span(self, event, start_ns: int, dur_ns: int, *,
                    ctx: Optional[TraceContext] = None, span_id: int = 0,
                    links=(), **tags) -> None:
        """Record a completed span with explicit timing (start from
        now_ns()) — for spans whose open/close sites are far apart,
        e.g. the primary's prepare_ok quorum wait."""
        ev = self._check(event, EventKind.span, tags)
        tags = dict(tags)
        if ctx is not None:
            sid = span_id or self.mint_span_id()
            tags["trace_id"] = fmt_trace_id(ctx.trace_id)
            tags["span_id"] = fmt_span_id(sid)
            tags["parent_id"] = fmt_span_id(ctx.parent_span_id)
        if links:
            tags["links"] = sorted(
                {t if isinstance(t, str) else fmt_trace_id(t)
                 for t in links})
        slot = self._lane(ev)
        self._busy[ev._hot[0]].discard(slot)
        self._record(ev, start_ns, dur_ns, tags, ev._hot[5] + slot)

    def keep_trace(self, trace_id, reason: str) -> None:
        """Tail retention: force-keep one trace regardless of the head-
        sampling decision (SLO breach, fallback/poison, recovery)."""
        tid = trace_id if isinstance(trace_id, str) else \
            fmt_trace_id(trace_id)
        if tid not in self.kept_traces:
            self.kept_traces[tid] = reason
            self.count(Event.trace_tail_keep, reason=reason)

    def begin(self, event, **tags) -> None:
        """Open a multi-tick phase span (view change, state sync,
        rebuild). A begin while the event is already open (same slot
        semantics as overlapping sync spans) first closes the open one."""
        ev = self._check(event, EventKind.span, tags)
        open_ = self._open.setdefault(ev.name, {})
        if len(open_) >= ev.slots:
            self.end(ev)  # saturated: close the oldest occurrence
        slot = self._lane(ev)
        open_[slot] = (_time.perf_counter_ns(), tags)

    def end(self, event, **tags) -> None:
        """Close the oldest open occurrence of a begin() span; a no-op
        when none is open (phases may end from several call sites)."""
        ev = self._check(event, EventKind.span, tags)
        open_ = self._open.get(ev.name)
        if not open_:
            return
        slot = min(open_)
        start_ns, begin_tags = open_.pop(slot)
        self._busy[ev.name].discard(slot)
        merged = dict(begin_tags, **tags)
        self._record(ev, start_ns, _time.perf_counter_ns() - start_ns,
                     merged, TID_BASE[ev] + slot)

    # --------------------------------------------------- counters / gauges

    def count(self, event, value: int = 1, **tags) -> None:
        ev = self._check(event, EventKind.counter, tags)
        self.emitted.add(ev.name)
        self.counters[ev.name] = self.counters.get(ev.name, 0) + value
        if self.statsd is not None:
            self.statsd.count(ev.name, value, **tags)
            self._maybe_flush()

    def gauge(self, event, value: float, **tags) -> None:
        ev = self._check(event, EventKind.gauge, tags)
        self.emitted.add(ev.name)
        self.gauges[ev.name] = value
        if self.statsd is not None:
            self.statsd.gauge(ev.name, value, **tags)
            self._maybe_flush()

    # ---------------------------------------------------------- histograms

    def observe(self, event, value: float, **tags) -> None:
        """Record one sample of a histogram-kind event (unit: whatever
        the event's doc declares). Span durations need no observe() —
        every span feeds its event's histogram at close."""
        ev = self._check(event, EventKind.histogram, tags)
        self.emitted.add(ev.name)
        self._histogram(ev, tags).record(value)
        self.aggregates.record(ev.name, float(value),
                               self._hist_tags(ev, tags))
        if self.statsd is not None:
            self._maybe_flush()

    def _hist_tags(self, ev: Event, tags: dict) -> dict:
        if not ev.hist_tags or not tags:
            return {}
        return {k: tags[k] for k in ev.hist_tags if k in tags}

    def _series_key(self, ev: Event, tags: dict) -> str:
        ht = self._hist_tags(ev, tags)
        return ev.name if not ht else ev.name + "|" + ",".join(
            f"{k}:{v}" for k, v in sorted(ht.items()))

    def _histogram(self, ev: Event, tags: dict) -> Histogram:
        key = self._series_key(ev, tags)
        h = self.histograms.get(key)
        if h is None:
            h = self.histograms[key] = Histogram()
            self.histogram_series[key] = (ev.name, self._hist_tags(ev, tags))
        return h

    # ----------------------------------------------------------- recording

    def _record(self, ev: Event, start_ns: int, dur_ns: int,
                tags: dict, tid: int) -> None:
        name = ev._hot[0]
        self.emitted.add(name)
        # Distributions first, ring second: accumulation at span close
        # must be complete BEFORE eviction can touch the span events,
        # so a halved ring never dents a histogram or an aggregate.
        dur_us = dur_ns / 1000.0
        # One hist-tags projection + series key, shared by histogram,
        # aggregates and exemplar (was computed up to four times).
        hts = ev._hot[4]
        ht = ({k: tags[k] for k in hts if k in tags}
              if hts and tags else {})
        key = name if not ht else name + "|" + ",".join(
            f"{k}:{v}" for k, v in sorted(ht.items()))
        h = self.histograms.get(key)
        if h is None:
            h = self.histograms[key] = Histogram()
            self.histogram_series[key] = (name, ht)
        h.record(dur_us)
        if self.statsd is None:
            # Inline count/sum/min/max update (the flush-interval
            # histogram is off without StatsD; see TimingAggregates).
            agg = self._agg
            a = agg.get(key)
            if a is None:
                agg[key] = [1, dur_us, dur_us, dur_us]
                self.aggregates._series[key] = (name, ht)
            else:
                a[0] += 1
                a[1] += dur_us
                if dur_us < a[2]:
                    a[2] = dur_us
                if dur_us > a[3]:
                    a[3] = dur_us
        else:
            self.aggregates.record(name, dur_us, ht, key=key)
        if "trace_id" in tags:
            # Exemplar: the last traced sample per series, linking a
            # latency distribution back to one concrete request trace.
            self.exemplars[key] = {
                "value": dur_us, "trace_id": tags["trace_id"]}
        if len(self.events) >= self.capacity:
            dropped = self.capacity // 2
            del self.events[:dropped]
            self.dropped_events += dropped
            # Self-describing truncation (satellite: a halved ring must
            # say so): a counter plus an instant marker INSIDE the trace.
            self.count(Event.trace_dropped_events, dropped)
            self.events.append({
                "name": Event.trace_dropped_events.name, "ph": "i",
                "ts": (start_ns + self._epoch_ns) / 1000.0,
                "pid": self.pid, "tid": 0, "s": "p",
                "args": {"dropped_total": self.dropped_events},
            })
        self.events.append({
            "name": name, "ph": "X",
            "ts": (start_ns + self._epoch_ns) / 1000.0,
            "dur": dur_us,
            "pid": self.pid, "tid": tid, "args": tags,
        })
        if self.statsd is not None:
            self._maybe_flush()

    def _maybe_flush(self) -> None:
        now = _time.perf_counter_ns()
        if now - self._last_flush_ns >= self.emit_interval_s * 1e9:
            self._last_flush_ns = now
            self.aggregates.flush_to(self.statsd)

    def flush_statsd(self) -> None:
        """Force-flush the timing aggregates (shutdown path)."""
        if self.statsd is not None:
            self._last_flush_ns = _time.perf_counter_ns()
            self.aggregates.flush_to(self.statsd)

    # --------------------------------------------------------------- dump

    def chrome_dict(self) -> dict:
        """Chrome/Perfetto-loadable document with process/thread names
        and the metadata block trace/merge.py keys on."""
        meta_events = [{
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": f"replica {self.pid}"},
        }]
        for tid in sorted(self._lanes_used):
            meta_events.append({
                "name": "thread_name", "ph": "M", "pid": self.pid,
                "tid": tid, "args": {"name": self._lanes_used[tid]},
            })
        return {
            "traceEvents": meta_events + self.events,
            "metadata": {
                "pid": self.pid,
                "clock_anchor_ns": self._epoch_ns,
                "dropped_events": self.dropped_events,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                # Tail-retention + exemplar state: merged across
                # documents so assemble_traces() keeps a trace any pid
                # flagged, and the metrics exposition can attach
                # exemplars after a merge.
                "kept_traces": dict(self.kept_traces),
                "exemplars": {k: dict(v)
                              for k, v in self.exemplars.items()},
                "aggregates": self.aggregates.snapshot(),
                # Cumulative per-series distributions: losslessly
                # mergeable across replica documents (trace/merge.py
                # adds bucket counts), eviction-proof unlike the ring.
                "histograms": {
                    key: {"event": self.histogram_series[key][0],
                          "tags": dict(self.histogram_series[key][1]),
                          **h.to_dict()}
                    for key, h in self.histograms.items()},
            },
        }

    def dump_chrome_trace(self, path: str) -> None:
        """Chrome/Perfetto-loadable trace (reference: --trace=file)."""
        with open(path, "w") as f:
            json.dump(self.chrome_dict(), f)


class _Span:
    __slots__ = ("tracer", "event", "tags", "start", "slot",
                 "ctx_in", "span_id", "_links")

    def __init__(self, tracer: Tracer, event: Event, tags: dict,
                 ctx: Optional[TraceContext] = None):
        self.tracer = tracer
        self.event = event
        self.tags = tags
        self.ctx_in = ctx
        self.span_id = 0
        self._links: set = set()

    @property
    def ctx(self) -> Optional[TraceContext]:
        """The context THIS span's children should carry (parent = this
        span's id); None when the span was opened without a context."""
        if self.ctx_in is None:
            return None
        return self.ctx_in.child(self.span_id)

    def link(self, trace_id) -> None:
        """Span link: tie this span into another request's trace (the
        batching fan-in — a window span links every constituent)."""
        self._links.add(trace_id if isinstance(trace_id, str)
                        else fmt_trace_id(trace_id))

    def __enter__(self):
        self.slot = self.tracer._lane(self.event)
        if self.ctx_in is not None:
            self.span_id = self.tracer.mint_span_id()
        self.start = _time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = _time.perf_counter_ns() - self.start
        hot = self.event._hot
        self.tracer._busy[hot[0]].discard(self.slot)
        tags = self.tags
        if self.ctx_in is not None or self._links:
            # Causal args ride beside the schema-checked tags; they are
            # reserved keys, not per-event schema, and never partition a
            # histogram series (only hist_tags do).
            tags = dict(tags)
            if self.ctx_in is not None:
                tags["trace_id"] = fmt_trace_id(self.ctx_in.trace_id)
                tags["span_id"] = fmt_span_id(self.span_id)
                tags["parent_id"] = fmt_span_id(self.ctx_in.parent_span_id)
            if self._links:
                tags["links"] = sorted(self._links)
        self.tracer._record(self.event, self.start, dur, tags,
                            hot[5] + self.slot)
        return False
