"""Cluster-wide trace merge: one Perfetto timeline from N replica traces.

The per-replica Chrome traces are already cross-process comparable: each
recording tracer anchors its span timestamps to the wall clock at
construction (trace/tracer.py `clock_anchor_ns`), so merging is
concatenation + a common rebase — no clock inference. `pid` identifies
the replica (set at tracer construction: `--trace` uses the replica id),
so one Perfetto load shows the whole cluster's commit/repair/rebuild
timeline with one process track per replica.

Used by testing/cluster.py (in-process clusters merge their replicas'
tracers directly) and testing/vortex.py (real processes dump
`r<i>.trace.json` on shutdown; `collect_merged_trace` merges the files).
"""

from __future__ import annotations

import json
from typing import Optional


def merge_traces(docs: list, rebase: bool = True) -> dict:
    """Merge Chrome-trace documents (as produced by
    Tracer.chrome_dict / dump_chrome_trace) into one.

    Replica identity must survive: documents with colliding pids are
    renumbered (their metadata events follow). With rebase=True every
    timed event is shifted so the earliest one lands at ts=0 — the
    common epoch-aligned base a multi-gigasecond wall-clock ts would
    otherwise bury."""
    events: list[dict] = []
    seen_pids: set = set()
    anchors: dict = {}
    dropped = 0
    for doc in docs:
        meta = doc.get("metadata", {})
        pid = meta.get("pid", 0)
        while pid in seen_pids:
            pid += 1  # collision: renumber deterministically
        seen_pids.add(pid)
        anchors[pid] = meta.get("clock_anchor_ns")
        dropped += meta.get("dropped_events", 0)
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            events.append(e)
    timed = [e for e in events if e.get("ph") != "M"]
    if rebase and timed:
        t0 = min(e["ts"] for e in timed)
        for e in timed:
            e["ts"] = round(e["ts"] - t0, 3)
    # Metadata first, then time order — Perfetto wants names early and
    # the acceptance checker wants a monotone stream.
    events.sort(key=lambda e: (0, 0) if e.get("ph") == "M"
                else (1, e["ts"]))
    return {
        "traceEvents": events,
        "metadata": {
            "replicas": sorted(seen_pids),
            "clock_anchors_ns": anchors,
            "dropped_events": dropped,
        },
    }


def merge_trace_files(paths: list, out_path: Optional[str] = None) -> dict:
    """Load per-replica trace files and merge; optionally write the
    merged document (the operator-facing `one Perfetto load` artifact)."""
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    merged = merge_traces(docs)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged
