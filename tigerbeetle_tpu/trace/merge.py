"""Cluster-wide trace merge: one Perfetto timeline from N replica traces.

The per-replica Chrome traces are already cross-process comparable: each
recording tracer anchors its span timestamps to the wall clock at
construction (trace/tracer.py `clock_anchor_ns`), so merging is
concatenation + a common rebase — no clock inference. `pid` identifies
the replica (set at tracer construction: `--trace` uses the replica id),
so one Perfetto load shows the whole cluster's commit/repair/rebuild
timeline with one process track per replica.

Used by testing/cluster.py (in-process clusters merge their replicas'
tracers directly) and testing/vortex.py (real processes dump
`r<i>.trace.json` on shutdown; `collect_merged_trace` merges the files).
"""

from __future__ import annotations

import json
import math
from typing import Optional

from .histogram import Histogram


def merge_traces(docs: list, rebase: bool = True) -> dict:
    """Merge Chrome-trace documents (as produced by
    Tracer.chrome_dict / dump_chrome_trace) into one.

    Replica identity must survive: documents with colliding pids are
    renumbered (their metadata events follow). With rebase=True every
    timed event is shifted so the earliest one lands at ts=0 — the
    common epoch-aligned base a multi-gigasecond wall-clock ts would
    otherwise bury."""
    events: list[dict] = []
    seen_pids: set = set()
    anchors: dict = {}
    dropped = 0
    histograms: dict = {}
    kept_traces: dict = {}
    exemplars: dict = {}
    for doc in docs:
        meta = doc.get("metadata", {})
        pid = meta.get("pid", 0)
        while pid in seen_pids:
            pid += 1  # collision: renumber deterministically
        seen_pids.add(pid)
        anchors[pid] = meta.get("clock_anchor_ns")
        dropped += meta.get("dropped_events", 0)
        # Tail retention is cluster-wide: a trace ANY pid flagged stays
        # kept in the merged document; exemplars keep the largest value
        # per series (the one a p99 bucket most plausibly links to).
        for tid, reason in (meta.get("kept_traces") or {}).items():
            kept_traces.setdefault(tid, reason)
        for key, ex in (meta.get("exemplars") or {}).items():
            cur = exemplars.get(key)
            if cur is None or ex.get("value", 0) > cur.get("value", 0):
                exemplars[key] = dict(ex)
        # Cluster-wide distributions: per-replica histograms with the
        # same series key ADD losslessly (integer bucket counts) — the
        # property the merged p99s in the acceptance check lean on.
        for key, d in (meta.get("histograms") or {}).items():
            h = Histogram.from_dict(d)
            if key in histograms:
                histograms[key]["_h"].merge(h)
            else:
                histograms[key] = {"event": d.get("event"),
                                   "tags": d.get("tags", {}), "_h": h}
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            events.append(e)
    timed = [e for e in events if e.get("ph") != "M"]
    if rebase and timed:
        t0 = min(e["ts"] for e in timed)
        for e in timed:
            e["ts"] = round(e["ts"] - t0, 3)
    # Metadata first, then time order — Perfetto wants names early and
    # the acceptance checker wants a monotone stream.
    events.sort(key=lambda e: (0, 0) if e.get("ph") == "M"
                else (1, e["ts"]))
    return {
        "traceEvents": events,
        "metadata": {
            "replicas": sorted(seen_pids),
            "clock_anchors_ns": anchors,
            "dropped_events": dropped,
            "kept_traces": kept_traces,
            "exemplars": exemplars,
            "histograms": {
                key: {"event": v["event"], "tags": v["tags"],
                      **v["_h"].to_dict()}
                for key, v in histograms.items()},
        },
    }


def span_quantile(doc: dict, name: str, q: float,
                  tag: Optional[str] = None) -> dict:
    """Exact nearest-rank quantile(s) of a span event's durations in a
    (merged) trace document, in MILLISECONDS. With `tag` the durations
    are grouped by that span-arg value ({tag_value: quantile_ms}); the
    "" key aggregates everything. The offline ground truth the endpoint
    histograms are checked against (within the histogram error bound)."""
    groups: dict = {"": []}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("name") != name:
            continue
        dur_ms = e.get("dur", 0.0) / 1000.0
        groups[""].append(dur_ms)
        if tag is not None:
            v = (e.get("args") or {}).get(tag)
            if v is not None:
                groups.setdefault(str(v), []).append(dur_ms)
    out = {}
    for k, durs in groups.items():
        if not durs:
            continue
        durs.sort()
        out[k] = durs[max(0, math.ceil(q * len(durs)) - 1)]
    return out


# The stage events a window's wall time is attributed to, in display
# order; "dispatch_retry" is the serving_dispatch span's backoff +
# retried attempts, visible as dispatch wall time beyond the window's
# own execute share.
CRITICAL_PATH_STAGES = (
    "admission_decision", "commit_prefetch", "commit_execute",
    "commit_compact", "commit_checkpoint", "journal_write",
    "serving_dispatch", "serving_epoch_verify",
    "serving_recovery_replay",
)


def critical_path(doc: dict, quantile: float = 0.9,
                  window_event: str = "window_commit") -> Optional[dict]:
    """Stage-share attribution for the slowest windows of a (merged)
    trace: which stage owns the tail.

    Walks the spans of the slowest-``(1-quantile)`` fraction of windows
    (default: the slowest decile). A "window" is a `window_event` span
    when the trace has any (serving traces); otherwise each replica's
    per-op commit group (commit_* spans sharing an `op` arg — replica
    traces, where the end-to-end unit is one committed prepare). Each
    selected window's wall time is attributed to the stage spans
    overlapping its [ts, ts+dur] interval on the same pid; time no
    stage claims is "other". Returns None when the trace has neither
    window spans nor commit groups."""
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    windows = [e for e in spans if e.get("name") == window_event]
    synthesized = False
    if not windows:
        windows = _commit_groups(spans)
        synthesized = True
    if not windows:
        return None
    windows.sort(key=lambda e: e["dur"])
    cut = int(len(windows) * quantile)
    slow = windows[cut:] or windows[-1:]
    stage_us: dict = {}
    other_us = 0.0
    total_us = 0.0
    for w in slow:
        t0, t1 = w["ts"], w["ts"] + w["dur"]
        total_us += w["dur"]
        claimed = 0.0
        # A synthesized window IS its commit_* members: attribute only
        # the group's own spans, not an interleaved neighbor op's.
        candidates = w["_members"] if synthesized else spans
        for s in candidates:
            if (s is w or s.get("pid") != w.get("pid")
                    or s.get("name") not in CRITICAL_PATH_STAGES):
                continue
            overlap = min(t1, s["ts"] + s["dur"]) - max(t0, s["ts"])
            if overlap > 0:
                name = s["name"]
                stage_us[name] = stage_us.get(name, 0.0) + overlap
                claimed += overlap
        other_us += max(0.0, w["dur"] - claimed)
    if other_us > 1e-9:
        stage_us["other"] = other_us
    denom = sum(stage_us.values()) or 1.0
    shares = {k: round(v / denom, 4)
              for k, v in sorted(stage_us.items(),
                                 key=lambda kv: -kv[1])}
    durs = sorted(e["dur"] for e in windows)
    p99_us = durs[max(0, math.ceil(0.99 * len(durs)) - 1)]
    return {
        "window_event": window_event if not synthesized else "commit_op",
        "windows_total": len(windows),
        "windows_analyzed": len(slow),
        "slow_quantile": quantile,
        "threshold_ms": round(slow[0]["dur"] / 1000.0, 3),
        "p99_ms": round(p99_us / 1000.0, 3),
        "stage_share": shares,
        "p99_owner": next(iter(shares), None),
    }


def _commit_groups(spans: list) -> list:
    """Synthesize window intervals from replica commit pipelines: the
    commit_* spans sharing one (pid, op) form a group whose envelope
    [first start, last end] is the per-op window."""
    groups: dict = {}
    for s in spans:
        if not str(s.get("name", "")).startswith("commit_"):
            continue
        op = (s.get("args") or {}).get("op")
        if op is None:
            continue
        groups.setdefault((s.get("pid"), op), []).append(s)
    out = []
    for (pid, op), members in groups.items():
        t0 = min(s["ts"] for s in members)
        t1 = max(s["ts"] + s["dur"] for s in members)
        out.append({"name": "commit_op", "ph": "X", "ts": t0,
                    "dur": t1 - t0, "pid": pid, "args": {"op": op},
                    "_members": members})
    return out


# --------------------------------------------------- causal assembly
# ISSUE 15: per-REQUEST attribution.  The stage quantiles above answer
# "which stage is slow"; assemble_traces answers "what happened to this
# request": group spans by propagated trace_id, correct per-pid clock
# skew from matched bus send/recv pairs, build the span tree, attach
# the batching fan-in via span links, and emit a per-request critical
# path (network vs quorum wait vs commit vs device dispatch).

_ROOT_PARENT = "0" * 16


def estimate_clock_offsets(doc: dict) -> dict:
    """Per-pid clock offsets (microseconds, relative to the lowest
    measured pid) estimated from matched bus_send/bus_recv span pairs:
    both ends of one frame tag the same `csum`, so for each directed
    pid pair the minimum observed (recv_start - send_end) is
    min_delay + offset; with both directions measured the symmetric
    NTP-style estimate cancels the delay, with one direction the
    min-delay term is assumed zero (biased by the true one-way minimum,
    but bounded by it).  Subtract offsets[pid] from that pid's ts to
    correct."""
    sends: dict = {}
    recvs: dict = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        csum = args.get("csum")
        if csum is None:
            continue
        if e.get("name") == "bus_send":
            sends.setdefault(csum, []).append(e)
        elif e.get("name") == "bus_recv":
            recvs.setdefault(csum, []).append(e)
    mins: dict = {}  # (src_pid, dst_pid) -> min one-way delta (us)
    for csum, rs in recvs.items():
        for r in rs:
            for s in sends.get(csum, ()):
                if s.get("pid") == r.get("pid"):
                    continue
                d = r["ts"] - (s["ts"] + s.get("dur", 0.0))
                k = (s.get("pid"), r.get("pid"))
                if k not in mins or d < mins[k]:
                    mins[k] = d
    pids = sorted({p for k in mins for p in k})
    if not pids:
        return {}
    offsets = {pids[0]: 0.0}
    frontier = [pids[0]]
    while frontier:
        a = frontier.pop()
        for b in pids:
            if b in offsets:
                continue
            d_ab = mins.get((a, b))
            d_ba = mins.get((b, a))
            if d_ab is None and d_ba is None:
                continue
            if d_ab is not None and d_ba is not None:
                rel = (d_ab - d_ba) / 2.0
            elif d_ab is not None:
                rel = d_ab
            else:
                rel = -d_ba
            offsets[b] = offsets[a] + rel
            frontier.append(b)
    return offsets


def causal_edges(trace: dict) -> list:
    """(parent_span, child_span) pairs of one assembled trace — the
    edges the skew-correction acceptance check walks."""
    by_id = {s["args"]["span_id"]: s for s in trace["spans"]}
    out = []
    for s in trace["spans"]:
        parent = by_id.get(s["args"].get("parent_id"))
        if parent is not None and parent is not s:
            out.append((parent, s))
    return out


def _request_critical_path(spans: list, linked: list) -> dict:
    """One request's wall-time attribution: where its latency actually
    went.  Stage sums come from the trace's own spans plus the window
    spans linked to it across the batching boundary; everything the
    stages do not claim (wire time both ways, queueing between stages,
    the reply delivery) is `network_other_us`."""
    roots = [s for s in spans
             if s["args"].get("parent_id") == _ROOT_PARENT]
    if roots:
        total = sum(s.get("dur", 0.0) for s in roots)
    else:
        t0 = min(s["ts"] for s in spans)
        t1 = max(s["ts"] + s.get("dur", 0.0) for s in spans)
        total = t1 - t0
    def _sum(names, pool):
        return sum(s.get("dur", 0.0) for s in pool
                   if s.get("name") in names)
    quorum = _sum({"commit_quorum"}, spans)
    commit = _sum({"commit_prefetch", "commit_execute", "commit_compact",
                   "journal_write"}, spans)
    dispatch = (_sum({"serving_dispatch", "window_commit",
                      "serving_recovery_replay"}, spans)
                + _sum({"serving_dispatch", "window_commit",
                        "serving_recovery_replay"}, linked))
    stages = {
        "quorum_wait_us": round(quorum, 3),
        "commit_us": round(commit, 3),
        "device_dispatch_us": round(dispatch, 3),
        "network_other_us": round(
            max(0.0, total - quorum - commit - dispatch), 3),
    }
    return {
        "total_us": round(total, 3),
        "stages": stages,
        "owner": max(stages, key=stages.get) if total else None,
    }


def assemble_traces(doc: dict, head_rate: float = 1.0, seed: int = 0,
                    skew_correct: bool = True) -> dict:
    """Group a (merged) trace document's causal spans by trace_id and
    build one span tree per request.

    Returns {"traces": [...], "clock_offsets_us": {...}, summary
    counts}.  Each trace carries its spans (ts skew-corrected), root,
    orphan spans (parent_id points nowhere — MUST be empty on a healthy
    run), the window spans linked to it across the batching boundary,
    the keep decision (deterministic head sample by trace_id hash, OR
    tail retention via the tracers' kept_traces metadata), and its
    per-request critical path."""
    from .context import head_sampled  # local: avoid import cycles

    offsets = estimate_clock_offsets(doc) if skew_correct else {}
    kept = dict((doc.get("metadata") or {}).get("kept_traces") or {})
    by_trace: dict = {}
    links_to: dict = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        tid = args.get("trace_id")
        lnks = args.get("links")
        if tid is None and not lnks:
            continue
        s = dict(e)
        off = offsets.get(e.get("pid"))
        if off:
            s["ts"] = round(s["ts"] - off, 3)
        if tid is not None:
            by_trace.setdefault(tid, []).append(s)
        for lt in lnks or ():
            if lt != tid:
                links_to.setdefault(lt, []).append(s)
    traces = []
    for tid, spans in sorted(by_trace.items()):
        spans.sort(key=lambda s: s["ts"])
        ids = {s["args"]["span_id"] for s in spans}
        roots = [s for s in spans
                 if s["args"].get("parent_id") == _ROOT_PARENT]
        orphans = [s for s in spans
                   if s["args"].get("parent_id") != _ROOT_PARENT
                   and s["args"].get("parent_id") not in ids]
        linked = sorted(links_to.get(tid, []), key=lambda s: s["ts"])
        reason = kept.get(tid)
        head = head_sampled(int(tid, 16), head_rate, seed)
        traces.append({
            "trace_id": tid,
            "spans": spans,
            "root": roots[0] if len(roots) == 1 else None,
            "roots": len(roots),
            "orphan_spans": orphans,
            "linked_spans": linked,
            "complete": len(roots) == 1 and not orphans,
            "kept": head or reason is not None,
            "keep_reason": ("tail:" + reason if reason is not None
                            else ("head" if head else None)),
            "critical_path": _request_critical_path(spans, linked),
        })
    return {
        "traces": traces,
        "clock_offsets_us": {str(k): round(v, 3)
                             for k, v in offsets.items()},
        "total": len(traces),
        "complete": sum(t["complete"] for t in traces),
        "kept_total": sum(t["kept"] for t in traces),
        "orphan_spans": sum(len(t["orphan_spans"]) for t in traces),
    }


def merge_trace_files(paths: list, out_path: Optional[str] = None) -> dict:
    """Load per-replica trace files and merge; optionally write the
    merged document (the operator-facing `one Perfetto load` artifact)."""
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    merged = merge_traces(docs)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged
