"""Cluster-wide trace merge: one Perfetto timeline from N replica traces.

The per-replica Chrome traces are already cross-process comparable: each
recording tracer anchors its span timestamps to the wall clock at
construction (trace/tracer.py `clock_anchor_ns`), so merging is
concatenation + a common rebase — no clock inference. `pid` identifies
the replica (set at tracer construction: `--trace` uses the replica id),
so one Perfetto load shows the whole cluster's commit/repair/rebuild
timeline with one process track per replica.

Used by testing/cluster.py (in-process clusters merge their replicas'
tracers directly) and testing/vortex.py (real processes dump
`r<i>.trace.json` on shutdown; `collect_merged_trace` merges the files).
"""

from __future__ import annotations

import json
import math
from typing import Optional

from .histogram import Histogram


def merge_traces(docs: list, rebase: bool = True) -> dict:
    """Merge Chrome-trace documents (as produced by
    Tracer.chrome_dict / dump_chrome_trace) into one.

    Replica identity must survive: documents with colliding pids are
    renumbered (their metadata events follow). With rebase=True every
    timed event is shifted so the earliest one lands at ts=0 — the
    common epoch-aligned base a multi-gigasecond wall-clock ts would
    otherwise bury."""
    events: list[dict] = []
    seen_pids: set = set()
    anchors: dict = {}
    dropped = 0
    histograms: dict = {}
    for doc in docs:
        meta = doc.get("metadata", {})
        pid = meta.get("pid", 0)
        while pid in seen_pids:
            pid += 1  # collision: renumber deterministically
        seen_pids.add(pid)
        anchors[pid] = meta.get("clock_anchor_ns")
        dropped += meta.get("dropped_events", 0)
        # Cluster-wide distributions: per-replica histograms with the
        # same series key ADD losslessly (integer bucket counts) — the
        # property the merged p99s in the acceptance check lean on.
        for key, d in (meta.get("histograms") or {}).items():
            h = Histogram.from_dict(d)
            if key in histograms:
                histograms[key]["_h"].merge(h)
            else:
                histograms[key] = {"event": d.get("event"),
                                   "tags": d.get("tags", {}), "_h": h}
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            events.append(e)
    timed = [e for e in events if e.get("ph") != "M"]
    if rebase and timed:
        t0 = min(e["ts"] for e in timed)
        for e in timed:
            e["ts"] = round(e["ts"] - t0, 3)
    # Metadata first, then time order — Perfetto wants names early and
    # the acceptance checker wants a monotone stream.
    events.sort(key=lambda e: (0, 0) if e.get("ph") == "M"
                else (1, e["ts"]))
    return {
        "traceEvents": events,
        "metadata": {
            "replicas": sorted(seen_pids),
            "clock_anchors_ns": anchors,
            "dropped_events": dropped,
            "histograms": {
                key: {"event": v["event"], "tags": v["tags"],
                      **v["_h"].to_dict()}
                for key, v in histograms.items()},
        },
    }


def span_quantile(doc: dict, name: str, q: float,
                  tag: Optional[str] = None) -> dict:
    """Exact nearest-rank quantile(s) of a span event's durations in a
    (merged) trace document, in MILLISECONDS. With `tag` the durations
    are grouped by that span-arg value ({tag_value: quantile_ms}); the
    "" key aggregates everything. The offline ground truth the endpoint
    histograms are checked against (within the histogram error bound)."""
    groups: dict = {"": []}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("name") != name:
            continue
        dur_ms = e.get("dur", 0.0) / 1000.0
        groups[""].append(dur_ms)
        if tag is not None:
            v = (e.get("args") or {}).get(tag)
            if v is not None:
                groups.setdefault(str(v), []).append(dur_ms)
    out = {}
    for k, durs in groups.items():
        if not durs:
            continue
        durs.sort()
        out[k] = durs[max(0, math.ceil(q * len(durs)) - 1)]
    return out


# The stage events a window's wall time is attributed to, in display
# order; "dispatch_retry" is the serving_dispatch span's backoff +
# retried attempts, visible as dispatch wall time beyond the window's
# own execute share.
CRITICAL_PATH_STAGES = (
    "commit_prefetch", "commit_execute", "commit_compact",
    "commit_checkpoint", "journal_write", "serving_dispatch",
    "serving_epoch_verify", "serving_recovery_replay",
)


def critical_path(doc: dict, quantile: float = 0.9,
                  window_event: str = "window_commit") -> Optional[dict]:
    """Stage-share attribution for the slowest windows of a (merged)
    trace: which stage owns the tail.

    Walks the spans of the slowest-``(1-quantile)`` fraction of windows
    (default: the slowest decile). A "window" is a `window_event` span
    when the trace has any (serving traces); otherwise each replica's
    per-op commit group (commit_* spans sharing an `op` arg — replica
    traces, where the end-to-end unit is one committed prepare). Each
    selected window's wall time is attributed to the stage spans
    overlapping its [ts, ts+dur] interval on the same pid; time no
    stage claims is "other". Returns None when the trace has neither
    window spans nor commit groups."""
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    windows = [e for e in spans if e.get("name") == window_event]
    synthesized = False
    if not windows:
        windows = _commit_groups(spans)
        synthesized = True
    if not windows:
        return None
    windows.sort(key=lambda e: e["dur"])
    cut = int(len(windows) * quantile)
    slow = windows[cut:] or windows[-1:]
    stage_us: dict = {}
    other_us = 0.0
    total_us = 0.0
    for w in slow:
        t0, t1 = w["ts"], w["ts"] + w["dur"]
        total_us += w["dur"]
        claimed = 0.0
        # A synthesized window IS its commit_* members: attribute only
        # the group's own spans, not an interleaved neighbor op's.
        candidates = w["_members"] if synthesized else spans
        for s in candidates:
            if (s is w or s.get("pid") != w.get("pid")
                    or s.get("name") not in CRITICAL_PATH_STAGES):
                continue
            overlap = min(t1, s["ts"] + s["dur"]) - max(t0, s["ts"])
            if overlap > 0:
                name = s["name"]
                stage_us[name] = stage_us.get(name, 0.0) + overlap
                claimed += overlap
        other_us += max(0.0, w["dur"] - claimed)
    if other_us > 1e-9:
        stage_us["other"] = other_us
    denom = sum(stage_us.values()) or 1.0
    shares = {k: round(v / denom, 4)
              for k, v in sorted(stage_us.items(),
                                 key=lambda kv: -kv[1])}
    durs = sorted(e["dur"] for e in windows)
    p99_us = durs[max(0, math.ceil(0.99 * len(durs)) - 1)]
    return {
        "window_event": window_event if not synthesized else "commit_op",
        "windows_total": len(windows),
        "windows_analyzed": len(slow),
        "slow_quantile": quantile,
        "threshold_ms": round(slow[0]["dur"] / 1000.0, 3),
        "p99_ms": round(p99_us / 1000.0, 3),
        "stage_share": shares,
        "p99_owner": next(iter(shares), None),
    }


def _commit_groups(spans: list) -> list:
    """Synthesize window intervals from replica commit pipelines: the
    commit_* spans sharing one (pid, op) form a group whose envelope
    [first start, last end] is the per-op window."""
    groups: dict = {}
    for s in spans:
        if not str(s.get("name", "")).startswith("commit_"):
            continue
        op = (s.get("args") or {}).get("op")
        if op is None:
            continue
        groups.setdefault((s.get("pid"), op), []).append(s)
    out = []
    for (pid, op), members in groups.items():
        t0 = min(s["ts"] for s in members)
        t1 = max(s["ts"] + s["dur"] for s in members)
        out.append({"name": "commit_op", "ph": "X", "ts": t0,
                    "dur": t1 - t0, "pid": pid, "args": {"op": op},
                    "_members": members})
    return out


def merge_trace_files(paths: list, out_path: Optional[str] = None) -> dict:
    """Load per-replica trace files and merge; optionally write the
    merged document (the operator-facing `one Perfetto load` artifact)."""
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    merged = merge_traces(docs)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged
