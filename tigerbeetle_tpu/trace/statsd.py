"""DogStatsD-format UDP emission + per-event timing aggregation.

reference: src/trace/statsd.zig — the reference does not emit one packet
per span; it AGGREGATES per-event timings (count/sum/min/max) between
emission intervals, flushes them as gauges, and resets the aggregates
after each emit so a quiet interval reads as zero instead of a stale
plateau. Counters and gauges emit immediately (the server aggregates
counts; gauges are last-write-wins anyway). All emission is best-effort:
a dead collector must never take a replica down with it.

Beyond the reference: each aggregate series carries a per-interval
log2 Histogram (trace/histogram.py), and the flush emits derived
p50/p95/p99/p999 as DogStatsD ``|ms`` TIMING lines — tagged with the
series' partition tags (route/tier on window spans) — next to the
count/sum/min/max gauges. The flush-and-reset contract is unchanged:
a quiet interval emits nothing stale.
"""

from __future__ import annotations

import socket

from .histogram import Histogram


class StatsD:
    """DogStatsD-format UDP emitter (reference: src/trace/statsd.zig)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "tb_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)

    def _emit(self, metric: str, value, kind: str, tags: dict) -> None:
        line = f"{self.prefix}.{metric}:{value}|{kind}"
        if tags:
            line += "|#" + ",".join(f"{k}:{v}" for k, v in tags.items())
        try:
            self.sock.sendto(line.encode(), self.addr)
        except OSError:
            pass  # metrics are best-effort

    def count(self, metric: str, value: int = 1, **tags) -> None:
        self._emit(metric, value, "c", tags)

    def gauge(self, metric: str, value: float, **tags) -> None:
        self._emit(metric, value, "g", tags)

    def timing(self, metric: str, ms: float, **tags) -> None:
        self._emit(metric, ms, "ms", tags)

    def close(self) -> None:
        self.sock.close()


class TimingAggregates:
    """Per-event span-duration aggregates between StatsD emits:
    count / sum / min / max in microseconds PLUS a per-interval log2
    histogram, reset after each flush (reference statsd.zig behavior:
    gauges reset after emit). Series are partitioned by the event's
    hist_tags values (e.g. window_commit route/tier) so per-class
    distributions survive the aggregation."""

    def __init__(self, with_hist: bool = True):
        # with_hist=False skips the per-interval histogram entirely —
        # it only feeds flush_to()'s percentile TIMING lines, so a
        # tracer with no StatsD attached need not pay a second
        # Histogram.record per span (the tracer's own cumulative
        # histograms are unaffected).
        self._agg: dict[str, list] = {}
        self._hist: dict[str, Histogram] = {}
        self._series: dict[str, tuple] = {}  # key -> (name, tags)
        self._with_hist = with_hist

    def record(self, name: str, dur_us: float, tags: dict = None,
               key: str = None) -> None:
        # `key` lets the tracer's span-close path pass its already-built
        # series key instead of paying the sorted-join twice per span.
        if key is None:
            key = name if not tags else name + "|" + ",".join(
                f"{k}:{v}" for k, v in sorted(tags.items()))
        a = self._agg.get(key)
        if a is None:
            self._agg[key] = [1, dur_us, dur_us, dur_us]
            self._series[key] = (name, dict(tags) if tags else {})
        else:
            a[0] += 1
            a[1] += dur_us
            if dur_us < a[2]:
                a[2] = dur_us
            if dur_us > a[3]:
                a[3] = dur_us
        if self._with_hist:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = Histogram()
            h.record(dur_us)

    def snapshot(self) -> dict:
        """{series: {count, sum_us, min_us, max_us}} without resetting.
        Untagged series key on the bare event name (the bench probe and
        chrome metadata shape); tagged series append |k:v pairs."""
        return {key: {"count": a[0], "sum_us": round(a[1], 3),
                      "min_us": round(a[2], 3), "max_us": round(a[3], 3)}
                for key, a in self._agg.items()}

    def flush_to(self, statsd: StatsD) -> None:
        """Emit every series as four gauges plus histogram-derived
        p50/p95/p99/p999 TIMING (``|ms``) lines carrying the series
        tags, then reset."""
        for key, a in self._agg.items():
            name, tags = self._series[key]
            statsd.gauge(f"trace.{name}.count", a[0], **tags)
            statsd.gauge(f"trace.{name}.sum_us", round(a[1], 3), **tags)
            statsd.gauge(f"trace.{name}.min_us", round(a[2], 3), **tags)
            statsd.gauge(f"trace.{name}.max_us", round(a[3], 3), **tags)
            h = self._hist.get(key)
            summary = h.summary() if h is not None else {}
            for q_name in ("p50", "p95", "p99", "p999"):
                q_us = summary.get(q_name)
                if q_us is not None:
                    statsd.timing(f"trace.{name}.{q_name}",
                                  round(q_us / 1000.0, 4), **tags)
        self._agg.clear()
        self._hist.clear()
        self._series.clear()
