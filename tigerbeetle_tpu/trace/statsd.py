"""DogStatsD-format UDP emission + per-event timing aggregation.

reference: src/trace/statsd.zig — the reference does not emit one packet
per span; it AGGREGATES per-event timings (count/sum/min/max) between
emission intervals, flushes them as gauges, and resets the aggregates
after each emit so a quiet interval reads as zero instead of a stale
plateau. Counters and gauges emit immediately (the server aggregates
counts; gauges are last-write-wins anyway). All emission is best-effort:
a dead collector must never take a replica down with it.
"""

from __future__ import annotations

import socket


class StatsD:
    """DogStatsD-format UDP emitter (reference: src/trace/statsd.zig)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "tb_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)

    def _emit(self, metric: str, value, kind: str, tags: dict) -> None:
        line = f"{self.prefix}.{metric}:{value}|{kind}"
        if tags:
            line += "|#" + ",".join(f"{k}:{v}" for k, v in tags.items())
        try:
            self.sock.sendto(line.encode(), self.addr)
        except OSError:
            pass  # metrics are best-effort

    def count(self, metric: str, value: int = 1, **tags) -> None:
        self._emit(metric, value, "c", tags)

    def gauge(self, metric: str, value: float, **tags) -> None:
        self._emit(metric, value, "g", tags)

    def timing(self, metric: str, ms: float, **tags) -> None:
        self._emit(metric, ms, "ms", tags)

    def close(self) -> None:
        self.sock.close()


class TimingAggregates:
    """Per-event span-duration aggregates between StatsD emits:
    count / sum / min / max in microseconds, reset after each flush
    (reference statsd.zig behavior: gauges reset after emit)."""

    def __init__(self):
        self._agg: dict[str, list] = {}

    def record(self, name: str, dur_us: float) -> None:
        a = self._agg.get(name)
        if a is None:
            self._agg[name] = [1, dur_us, dur_us, dur_us]
        else:
            a[0] += 1
            a[1] += dur_us
            if dur_us < a[2]:
                a[2] = dur_us
            if dur_us > a[3]:
                a[3] = dur_us

    def snapshot(self) -> dict:
        """{event: {count, sum_us, min_us, max_us}} without resetting."""
        return {name: {"count": a[0], "sum_us": round(a[1], 3),
                       "min_us": round(a[2], 3), "max_us": round(a[3], 3)}
                for name, a in self._agg.items()}

    def flush_to(self, statsd: StatsD) -> None:
        """Emit every aggregate as four gauges, then reset."""
        for name, a in self._agg.items():
            statsd.gauge(f"trace.{name}.count", a[0])
            statsd.gauge(f"trace.{name}.sum_us", round(a[1], 3))
            statsd.gauge(f"trace.{name}.min_us", round(a[2], 3))
            statsd.gauge(f"trace.{name}.max_us", round(a[3], 3))
        self._agg.clear()
