"""Multi-window multi-burn-rate alerting over the SLO objectives.

perf/slo.json grows an ``alerts`` section: each rule watches one
declared objective and fires SRE-style — only when BOTH a fast burn
window (catches cliffs in minutes) and a slow burn window (filters
one-tick blips) exceed their burn thresholds (Google SRE workbook ch.5,
in commit-window-tick time instead of wall time, because window ticks
are the unit the deterministic core advances by and the unit every
other plane — telemetry, flight recorder, epoch verification — already
counts in).

Mechanics per tick (a tick = one committed serving window, decimated
by ``tick_every``):

- the rule's objective is evaluated over the DELTA of its histogram
  series since the previous tick (cumulative histograms subtract
  losslessly — integer bucket counts), so a burn is about what just
  happened, not diluted by the whole run's history;
- a tick with no new samples is UNKNOWN: it consumes no error budget
  and never resolves an alert (exactly like the SLO engine's run-
  granular burn accounting);
- breach bits land in a ring of ``slow_window`` ticks; the rule fires
  when fast-window burn >= fast_burn AND slow-window burn >= slow_burn
  (with at least ``fast_window`` known ticks), and resolves after
  ``hysteresis`` consecutive healthy known ticks.

A firing alert is a TYPED object, not a log line: severity
(page | ticket), a runbook anchor into docs/operating/monitoring.md,
the breaching value and both burn rates, and the exemplar trace ids of
the breaching series — which it force-keeps via tail retention
(``alert:<rule>`` reason) so a 1%-head-sampled deployment still holds
every trace behind the page. A page-severity firing additionally
freezes a flight-recorder artifact (``alert_<rule>`` cause): the
post-mortem starts pre-assembled.

Dead rules cannot ship: a rule naming an objective perf/slo.json does
not declare is a load-time ValueError, proven RED by the gate's
profile leg.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

from .event import Event, EventKind
from .histogram import Histogram
from .slo import (DEFAULT_SLO_PATH, Objective, _exemplar_trace_ids,
                  _series_for, load_objectives)

SEVERITIES = ("page", "ticket")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One burn-rate rule over a declared SLO objective."""

    name: str
    objective: str           # perf/slo.json objective name
    fast_window: int         # ticks; the cliff detector
    slow_window: int         # ticks; the blip filter (> fast_window)
    fast_burn: float         # breach fraction to trip the fast window
    slow_burn: float         # breach fraction to trip the slow window
    severity: str = "ticket"  # page | ticket
    hysteresis: int = 8      # healthy known ticks to resolve
    runbook: str = ""        # anchor into docs/operating/monitoring.md
    doc: str = ""


@dataclasses.dataclass
class Alert:
    """A typed firing: everything the responder needs, pre-assembled."""

    rule: str
    objective: str
    severity: str
    runbook: str
    fired_tick: int
    value: Optional[float]       # breaching delta quantile (obj. unit)
    threshold: float
    fast_burn_rate: float
    slow_burn_rate: float
    trace_ids: list = dataclasses.field(default_factory=list)
    flight_path: Optional[str] = None
    resolved_tick: Optional[int] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def load_alert_rules(path: Optional[str] = None) -> dict:
    """Parse perf/slo.json's ``alerts`` section against its own
    objectives. Returns {"rules": [AlertRule...], "objectives":
    {name: Objective}}. A rule referencing an undeclared objective, an
    unknown severity, or inverted windows is a ValueError — the
    dead-rule RED the gate's profile leg proves."""
    import json

    path = path or DEFAULT_SLO_PATH
    loaded = load_objectives(path)
    by_name = {o.name: o for o in loaded["objectives"]}
    with open(path) as f:
        raw = json.load(f)
    rules = []
    seen = set()
    for r in raw.get("alerts", []):
        name = r.get("name")
        if not name or name in seen:
            raise ValueError(
                f"slo.json alerts: missing/duplicate rule name {name!r}")
        seen.add(name)
        obj = r.get("objective")
        if obj not in by_name:
            raise ValueError(
                f"slo.json alert {name!r}: objective {obj!r} is not "
                f"declared in {sorted(by_name)} — a dead rule nothing "
                f"can ever evaluate")
        sev = r.get("severity", "ticket")
        if sev not in SEVERITIES:
            raise ValueError(
                f"slo.json alert {name!r}: severity {sev!r} not in "
                f"{SEVERITIES}")
        fast_w = int(r.get("fast_window", 8))
        slow_w = int(r.get("slow_window", 32))
        if not 0 < fast_w < slow_w:
            raise ValueError(
                f"slo.json alert {name!r}: windows must satisfy "
                f"0 < fast ({fast_w}) < slow ({slow_w})")
        fast_b = float(r.get("fast_burn", 0.5))
        slow_b = float(r.get("slow_burn", 0.25))
        for label, b in (("fast_burn", fast_b), ("slow_burn", slow_b)):
            if not 0.0 < b <= 1.0:
                raise ValueError(
                    f"slo.json alert {name!r}: {label} {b} not in (0, 1]")
        if not r.get("runbook"):
            raise ValueError(
                f"slo.json alert {name!r}: a rule must carry a runbook "
                f"anchor (docs/operating/monitoring.md#...)")
        rules.append(AlertRule(
            name=name, objective=obj, fast_window=fast_w,
            slow_window=slow_w, fast_burn=fast_b, slow_burn=slow_b,
            severity=sev, hysteresis=int(r.get("hysteresis", 8)),
            runbook=str(r["runbook"]), doc=r.get("doc", "")))
    return {"rules": rules, "objectives": by_name}


def _delta_histogram(cur: Histogram, prev_buckets: dict,
                     prev_zero: int) -> Histogram:
    """The lossless difference of two cumulative snapshots of the same
    series (integer bucket subtraction). min/max are bucket-mid bounds
    — exact extremes don't subtract, and quantiles only need the
    clip."""
    from .histogram import bucket_mid

    d = Histogram()
    for i, n in cur.buckets.items():
        dn = n - prev_buckets.get(i, 0)
        if dn > 0:
            d.buckets[i] = dn
    d.zero_count = max(0, cur.zero_count - prev_zero)
    d.count = d.zero_count + sum(d.buckets.values())
    if d.buckets:
        d.min = 0.0 if d.zero_count else bucket_mid(min(d.buckets))
        d.max = bucket_mid(max(d.buckets))
    elif d.count:
        d.min = d.max = 0.0
    return d


class AlertEngine:
    """The per-process alert evaluator the serving supervisor ticks
    once per committed window (decimated by ``tick_every`` so rule
    evaluation never shows up in the dispatch overhead budget)."""

    def __init__(self, rules=None, objectives=None, *, tracer=None,
                 flight=None, tick_every: int = 4,
                 path: Optional[str] = None):
        if rules is None:
            loaded = load_alert_rules(path)
            rules = loaded["rules"]
            objectives = loaded["objectives"]
        if objectives is None:
            objectives = {}
        missing = [r.name for r in rules if r.objective not in objectives]
        if missing:
            raise ValueError(f"alert rules without objectives: {missing}")
        self.rules = list(rules)
        self.objectives = dict(objectives)
        self.tracer = tracer
        self.flight = flight
        self.tick_every = max(1, int(tick_every))
        self.windows = 0          # windows seen (tick() calls)
        self.ticks = 0            # evaluations actually run
        self.fired: list = []     # every Alert ever fired, in order
        self.active: dict = {}    # rule name -> Alert
        self._bits: dict = {r.name: deque(maxlen=r.slow_window)
                            for r in self.rules}
        self._healthy: dict = {r.name: 0 for r in self.rules}
        self._snap: dict = {}     # rule name -> (buckets, zero)
        self._last: dict = {}     # rule name -> last evaluation row

    def bind(self, tracer, flight=None) -> None:
        """Late wiring (the supervisor owns tracer + flight recorder)."""
        self.tracer = tracer
        if flight is not None:
            self.flight = flight

    # ----------------------------------------------------------- ticking

    def tick(self) -> list:
        """Advance one committed window; every ``tick_every``-th call
        evaluates all rules. Returns alerts newly fired on this call."""
        self.windows += 1
        if (self.windows - 1) % self.tick_every:
            return []
        if self.tracer is None or not getattr(
                self.tracer, "histogram_series", None):
            return []
        self.ticks += 1
        fired_now = []
        for rule in self.rules:
            alert = self._tick_rule(rule)
            if alert is not None:
                fired_now.append(alert)
        return fired_now

    def _tick_rule(self, rule: AlertRule):
        o = self.objectives[rule.objective]
        cur = _series_for(self.tracer, o)
        prev_buckets, prev_zero = self._snap.get(rule.name, ({}, 0))
        self._snap[rule.name] = (dict(cur.buckets), cur.zero_count)
        delta = _delta_histogram(cur, prev_buckets, prev_zero)
        bits = self._bits[rule.name]
        if delta.count == 0:
            bits.append(None)     # unknown: consumes no error budget
            return None
        value = delta.quantile(o.quantile)
        if value is not None and o.unit == "ms" and \
                Event[o.event].kind is EventKind.span:
            value /= 1000.0       # span histograms carry microseconds
        breach = value is not None and value > o.threshold
        bits.append(bool(breach))
        self._last[rule.name] = {"value": value, "breach": breach,
                                 "tick": self.ticks}
        if rule.name in self.active:
            self._maybe_resolve(rule, breach)
            return None
        return self._maybe_fire(rule, o, value)

    def _burn(self, bits, window: int):
        known = [b for b in list(bits)[-window:] if b is not None]
        if not known:
            return 0.0, 0
        return sum(known) / len(known), len(known)

    def _maybe_fire(self, rule: AlertRule, o: Objective, value):
        bits = self._bits[rule.name]
        fast, fast_n = self._burn(bits, rule.fast_window)
        slow, _ = self._burn(bits, rule.slow_window)
        known_total = sum(1 for b in bits if b is not None)
        if known_total < rule.fast_window:
            return None           # not enough evidence to page anyone
        if fast < rule.fast_burn or slow < rule.slow_burn:
            return None
        alert = Alert(
            rule=rule.name, objective=rule.objective,
            severity=rule.severity, runbook=rule.runbook,
            fired_tick=self.ticks, value=value, threshold=o.threshold,
            fast_burn_rate=round(fast, 4), slow_burn_rate=round(slow, 4))
        if self.tracer is not None:
            self.tracer.count(Event.alert_fired, rule=rule.name,
                              severity=rule.severity)
            for tid in _exemplar_trace_ids(self.tracer, o):
                self.tracer.keep_trace(tid, reason=f"alert:{rule.name}")
                alert.trace_ids.append(tid)
        if rule.severity == "page" and self.flight is not None:
            alert.flight_path = self.flight.dump(f"alert_{rule.name}")
        self.active[rule.name] = alert
        self.fired.append(alert)
        self._healthy[rule.name] = 0
        return alert

    def _maybe_resolve(self, rule: AlertRule, breach: bool) -> None:
        if breach:
            self._healthy[rule.name] = 0
            return
        self._healthy[rule.name] += 1
        if self._healthy[rule.name] >= rule.hysteresis:
            self.active.pop(rule.name).resolved_tick = self.ticks
            self._healthy[rule.name] = 0

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        return {
            "rules": len(self.rules),
            "windows": self.windows,
            "ticks": self.ticks,
            "tick_every": self.tick_every,
            "fired_total": len(self.fired),
            "active": sorted(self.active),
            "alerts": [a.to_dict() for a in self.fired],
            "last": {k: dict(v) for k, v in self._last.items()},
        }
