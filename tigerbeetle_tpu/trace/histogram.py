"""Log2-bucketed mergeable latency histograms (HDR-style, pure numpy).

The reference's timing aggregates (src/trace/statsd.zig) stop at
count/sum/min/max — enough for dashboards, useless for tails. This is
the repo's distribution primitive: a FIXED bucket layout shared by every
histogram ever constructed, so histograms merge LOSSLESSLY across
replicas, processes, and runs by adding bucket counts (associative and
commutative — the property the cluster-wide trace merge and the
Prometheus exposition both lean on).

Layout: each octave [2^k, 2^(k+1)) is split into ``SUB = 2**SUB_BITS``
geometric sub-buckets, i.e. bucket i covers [2^(i/SUB), 2^((i+1)/SUB)).
Reporting a bucket by its geometric midpoint bounds the relative error
of any reconstructed quantile by ``REL_ERROR`` = 2^(1/(2*SUB)) - 1
(~1.09% at SUB_BITS=5) — the "1-2% relative error" HDR contract, at a
cost of SUB buckets per octave actually touched (sparse dict storage).

Values are unit-agnostic floats (span durations feed microseconds;
the replay-length histogram feeds window counts). Zero/negative values
land in a dedicated zero bucket; exact min/max/sum/count ride along so
p0/p100 and means are exact, not bucket-rounded.
"""

from __future__ import annotations

import math

import numpy as np

SUB_BITS = 5                      # sub-buckets per octave = 32
SUB = 1 << SUB_BITS
# Bucket index range: 2^-32 .. 2^48 covers sub-nanosecond (in us) up to
# ~8.9 years (in us); out-of-range values clamp to the edge buckets.
IDX_MIN = -32 * SUB
IDX_MAX = 48 * SUB
# Half-width of one geometric bucket around its midpoint.
REL_ERROR = 2.0 ** (1.0 / (2 * SUB)) - 1.0


def bucket_index(value: float) -> int:
    """Bucket index of a positive value: floor(log2(v) * SUB)."""
    return min(IDX_MAX, max(IDX_MIN, math.floor(math.log2(value) * SUB)))


def bucket_upper(index: int) -> float:
    """Exclusive upper bound of bucket `index` (Prometheus `le`)."""
    return 2.0 ** ((index + 1) / SUB)


def bucket_mid(index: int) -> float:
    """Geometric midpoint — the reported representative value."""
    return 2.0 ** ((index + 0.5) / SUB)


class Histogram:
    """Fixed-layout log2 histogram: sparse bucket counts plus exact
    count/sum/min/max. record() is O(1); record_many() is vectorized
    numpy for bench loops; merge() adds integer bucket counts."""

    __slots__ = ("buckets", "zero_count", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets: dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None

    # ---------------------------------------------------------- recording

    def record(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        if value <= 0.0:
            self.zero_count += 1
            return
        i = bucket_index(value)
        self.buckets[i] = self.buckets.get(i, 0) + 1

    def record_many(self, values) -> None:
        vals = np.asarray(values, dtype=np.float64).ravel()
        if vals.size == 0:
            return
        self.count += int(vals.size)
        self.sum += float(vals.sum())
        lo = float(vals.min())
        hi = float(vals.max())
        if self.min is None or lo < self.min:
            self.min = lo
        if self.max is None or hi > self.max:
            self.max = hi
        pos = vals[vals > 0.0]
        self.zero_count += int(vals.size - pos.size)
        if pos.size:
            idx = np.clip(np.floor(np.log2(pos) * SUB).astype(np.int64),
                          IDX_MIN, IDX_MAX)
            uniq, counts = np.unique(idx, return_counts=True)
            for i, n in zip(uniq.tolist(), counts.tolist()):
                self.buckets[i] = self.buckets.get(i, 0) + n

    # ------------------------------------------------------------ merging

    def merge(self, other: "Histogram") -> "Histogram":
        """Accumulate `other` into self (lossless: integer bucket adds).
        Returns self for chaining."""
        for i, n in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + n
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None
                                      or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None
                                      or other.max > self.max):
            self.max = other.max
        return self

    @classmethod
    def merged(cls, hists) -> "Histogram":
        out = cls()
        for h in hists:
            out.merge(h)
        return out

    # ---------------------------------------------------------- quantiles

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile from bucket midpoints, clipped to the
        exact observed [min, max] (so p0/p100 and one-sample histograms
        are exact; interior quantiles carry <= REL_ERROR)."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(q * self.count))
        seen = self.zero_count
        if target <= seen:
            # zero_count > 0 implies min <= 0; the non-positive samples
            # are not sub-bucketed, so report the exact floor.
            return self.min
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if target <= seen:
                return min(self.max, max(self.min, bucket_mid(i)))
        return self.max

    def summary(self) -> dict:
        """The flushed percentile set (p50/p95/p99/p999) plus exact
        count/sum/min/max — the StatsD + bench record shape."""
        out = {"count": self.count,
               "sum": round(self.sum, 3),
               "min": None if self.min is None else round(self.min, 3),
               "max": None if self.max is None else round(self.max, 3)}
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99),
                        ("p999", 0.999)):
            v = self.quantile(q)
            out[name] = None if v is None else round(v, 3)
        return out

    # --------------------------------------------------------- exposition

    def cumulative(self) -> list:
        """[(upper_bound, cumulative_count), ...] over non-empty buckets
        (zero bucket first when present) — the Prometheus
        `_bucket{le=...}` series; the +Inf bucket is the total count."""
        out = []
        cum = 0
        if self.zero_count:
            cum += self.zero_count
            out.append((0.0, cum))
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            out.append((bucket_upper(i), cum))
        return out

    # ------------------------------------------------------- serialization

    def to_dict(self) -> dict:
        return {
            "sub_bits": SUB_BITS,
            "buckets": {str(i): n for i, n in sorted(self.buckets.items())},
            "zero": self.zero_count,
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        assert d.get("sub_bits", SUB_BITS) == SUB_BITS, \
            "histogram layout mismatch (SUB_BITS changed?)"
        h = cls()
        h.buckets = {int(i): int(n) for i, n in d.get("buckets", {}).items()}
        h.zero_count = int(d.get("zero", 0))
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = d.get("min")
        h.max = d.get("max")
        return h
