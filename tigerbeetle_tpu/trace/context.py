"""Trace-context: the compact causal identity one request carries on
the wire from client submit to device dispatch and back.

The reference implementation has no distributed tracer; this module is
the graft's own observability plane (ISSUE 15), modelled on the W3C
trace-context shape but packed for the 256-byte VSR header's reserved
region rather than an HTTP header:

wire block (``CTX_WIRE_SIZE`` = 28 bytes, little-endian ``<BBH16sQ``)::

    off  size  field
    0    1     magic          CTX_MAGIC (0xC7) — absent/garbage => no ctx
    1    1     flags          bit 0 = sampled (head decision at mint)
    2    2     mini-checksum  crc32(flags + trace_id + parent) & 0xFFFF
    4    16    trace_id       u128, minted once per client request
    20   8     parent_span_id u64, span the receiver should parent to

The block lives OUTSIDE the header checksum (the checksum is computed
over a zeroed reserved region), so a corrupt or truncated context
degrades to "unsampled" — ``TraceContext.unpack`` returns None and the
payload parse is unaffected.  That is the fuzzer's contract: tracing
may never take down the bus.

Identity is deterministic end to end: trace ids hash (client_id,
request_number, seed) and the head-sampling decision hashes the trace
id against a seedable rate, so a run reproduces its sampling decisions
exactly and the deterministic core needs no wall clock or unseeded RNG.
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
import zlib

CTX_MAGIC = 0xC7
FLAG_SAMPLED = 0x01

_CTX_FMT = struct.Struct("<BBH16sQ")
CTX_WIRE_SIZE = _CTX_FMT.size
assert CTX_WIRE_SIZE == 28


def _mini_checksum(flags: int, trace_id: int, parent_span_id: int) -> int:
    payload = (bytes((flags,)) + trace_id.to_bytes(16, "little")
               + parent_span_id.to_bytes(8, "little"))
    return zlib.crc32(payload) & 0xFFFF


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's causal coordinates: (trace, parent span, flags)."""

    trace_id: int
    parent_span_id: int = 0
    flags: int = FLAG_SAMPLED

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    def child(self, span_id: int) -> "TraceContext":
        """The context a span hands to ITS children (bus hops, sub-work)."""
        return TraceContext(self.trace_id, span_id, self.flags)

    def pack(self) -> bytes:
        return _CTX_FMT.pack(
            CTX_MAGIC, self.flags & 0xFF,
            _mini_checksum(self.flags & 0xFF, self.trace_id,
                           self.parent_span_id),
            self.trace_id.to_bytes(16, "little"), self.parent_span_id)

    @classmethod
    def unpack(cls, data: bytes) -> "TraceContext | None":
        """None (never an exception) on anything but a pristine block."""
        if len(data) < CTX_WIRE_SIZE:
            return None
        try:
            magic, flags, mini, tid, parent = _CTX_FMT.unpack(
                data[:CTX_WIRE_SIZE])
        except struct.error:  # pragma: no cover - length guarded above
            return None
        if magic != CTX_MAGIC:
            return None
        trace_id = int.from_bytes(tid, "little")
        if mini != _mini_checksum(flags, trace_id, parent):
            return None
        return cls(trace_id=trace_id, parent_span_id=parent, flags=flags)


def fmt_trace_id(trace_id: int) -> str:
    return f"{trace_id:032x}"


def fmt_span_id(span_id: int) -> str:
    return f"{span_id:016x}"


def mint_trace_id(client_id: int, request_number: int, seed: int = 0) -> int:
    """Deterministic u128 trace id — unique per (client, request) and
    reproducible under a fixed seed, so the deterministic core never
    needs randomness to trace."""
    h = hashlib.blake2s(
        client_id.to_bytes(16, "little")
        + request_number.to_bytes(8, "little")
        + seed.to_bytes(8, "little", signed=False),
        digest_size=16).digest()
    return int.from_bytes(h, "little") or 1


def head_sampled(trace_id: int, rate: float, seed: int = 0) -> bool:
    """Deterministic head-sampling decision: hash the trace id against
    the rate.  rate >= 1.0 keeps everything, <= 0.0 nothing; the same
    (trace_id, seed) always lands on the same side, so every pid in the
    cluster agrees without coordination."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = hashlib.blake2s(trace_id.to_bytes(16, "little")
                        + seed.to_bytes(8, "little"),
                        digest_size=8).digest()
    return int.from_bytes(h, "little") < rate * 2.0**64


def mint_context(client_id: int, request_number: int, *,
                 head_rate: float = 1.0, seed: int = 0) -> TraceContext:
    """Mint the root context for one client request.  The context is
    ALWAYS minted (tail retention needs identity on every request);
    only the sampled flag reflects the head decision."""
    trace_id = mint_trace_id(client_id, request_number, seed)
    flags = FLAG_SAMPLED if head_sampled(trace_id, head_rate, seed) else 0
    return TraceContext(trace_id=trace_id, parent_span_id=0, flags=flags)
