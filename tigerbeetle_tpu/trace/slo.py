"""SLO engine: declared latency objectives, evaluation, burn rates.

`perf/slo.json` declares the service-level objectives (the ROADMAP's
"per-class p50/p99 latency SLOs tracked in bench + devhub"). Schema:

    {
      "burn_window_runs": 8,          # sliding window for burn rates
      "burn_budget": 0.25,            # tolerated breach fraction
      "objectives": [
        {"name": "chain_window_p99_ms",
         "event": "window_commit",     # MUST be a catalog member
         "tags": {"route": "chain"},   # histogram series filter
         "quantile": 0.99,
         "threshold": 250.0,           # in `unit`
         "unit": "ms",                 # ms (span durations) | raw
         "doc": "..."}
      ]
    }

Every objective references a trace-catalog event; an off-catalog event
is a hard error at load time (a "dead SLO" — an objective nothing can
ever feed — is RED in the gate's metrics leg). Evaluation reads the
recording tracer's cumulative histograms: an objective with no samples
is `ok: None` (unknown), a breached one emits the `slo_breach` counter.
Burn-rate accounting is run-granular: over the trailing
`burn_window_runs` bench/devhub records, the burn rate is the fraction
of evaluated runs in breach; burn above `burn_budget` (or a breach in
the latest run) raises the devhub panel's badge.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from .event import Event, EventKind, lookup
from .histogram import Histogram

DEFAULT_SLO_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "perf",
    "slo.json")


@dataclasses.dataclass(frozen=True)
class Objective:
    name: str
    event: str
    quantile: float
    threshold: float
    tags: dict = dataclasses.field(default_factory=dict)
    unit: str = "ms"
    doc: str = ""


def load_objectives(path: Optional[str] = None) -> dict:
    """Parse perf/slo.json -> {"objectives": [Objective...],
    "burn_window_runs": int, "burn_budget": float}. Raises ValueError
    on schema violations or objectives referencing off-catalog events
    (dead SLOs cannot ship — the gate metrics leg runs exactly this)."""
    path = path or DEFAULT_SLO_PATH
    with open(path) as f:
        raw = json.load(f)
    objectives = []
    seen = set()
    for o in raw.get("objectives", []):
        name = o.get("name")
        if not name or name in seen:
            raise ValueError(f"slo.json: missing/duplicate name {name!r}")
        seen.add(name)
        try:
            ev = lookup(o["event"])
        except KeyError as e:
            raise ValueError(
                f"slo.json objective {name!r}: {e.args[0]}") from e
        if ev.kind not in (EventKind.span, EventKind.histogram):
            raise ValueError(
                f"slo.json objective {name!r}: event {ev.name} is a "
                f"{ev.kind.value}; objectives need a distribution "
                f"(span or histogram)")
        tags = o.get("tags") or {}
        if not set(tags) <= set(ev.hist_tags):
            raise ValueError(
                f"slo.json objective {name!r}: tags {sorted(tags)} are "
                f"not histogram dimensions of {ev.name} "
                f"(has {list(ev.hist_tags)})")
        q = float(o.get("quantile", 0.99))
        if not 0.0 < q <= 1.0:
            raise ValueError(f"slo.json objective {name!r}: quantile {q}")
        objectives.append(Objective(
            name=name, event=ev.name, quantile=q,
            threshold=float(o["threshold"]), tags=dict(tags),
            unit=o.get("unit", "ms"), doc=o.get("doc", "")))
    if not objectives:
        raise ValueError(f"slo.json at {path} declares no objectives")
    return {
        "objectives": objectives,
        "burn_window_runs": int(raw.get("burn_window_runs", 8)),
        "burn_budget": float(raw.get("burn_budget", 0.25)),
    }


def _series_for(tracer, objective: Objective) -> Histogram:
    """Merge the tracer histogram series matching the objective's event
    + tag filter (an empty filter aggregates every series of the
    event)."""
    out = Histogram()
    for key, (name, tags) in tracer.histogram_series.items():
        if name != objective.event:
            continue
        if any(tags.get(k) != v for k, v in objective.tags.items()):
            continue
        out.merge(tracer.histograms[key])
    return out


def _exemplar_trace_ids(tracer, objective: Objective) -> list:
    """Trace ids exemplifying the objective's series: the tracer keeps
    one exemplar (latest traced sample) per histogram series; a breach
    tail-keeps exactly these, tying the breached distribution back to
    concrete causal request traces."""
    out = []
    exemplars = getattr(tracer, "exemplars", None)
    if not exemplars:
        return out
    for key, (name, tags) in tracer.histogram_series.items():
        if name != objective.event:
            continue
        if any(tags.get(k) != v for k, v in objective.tags.items()):
            continue
        ex = exemplars.get(key)
        if ex and ex.get("trace_id"):
            out.append(ex["trace_id"])
    return out


def evaluate(tracer, objectives, emit_to=None) -> list:
    """Evaluate objectives against a recording tracer's cumulative
    histograms. Returns one row per objective:
    {name, event, quantile, value, threshold, unit, count, ok} with
    ok=None when the series is empty (unknown, not a breach). With
    `emit_to` (a tracer), each breach counts the `slo_breach` catalog
    event tagged with the objective name, and tail-retains the breached
    series' exemplar traces (keep_trace reason "slo_breach") so a
    1%-head-sampled deployment still keeps every breach's trace."""
    rows = []
    for o in objectives:
        h = _series_for(tracer, o)
        value = h.quantile(o.quantile)
        if value is not None and o.unit == "ms" and Event[o.event].kind \
                is EventKind.span:
            value /= 1000.0  # span histograms accumulate microseconds
        ok = None if value is None else bool(value <= o.threshold)
        if ok is False and emit_to is not None:
            emit_to.count(Event.slo_breach, objective=o.name)
            for tid in _exemplar_trace_ids(tracer, o):
                emit_to.keep_trace(tid, reason="slo_breach")
        rows.append({
            "name": o.name, "event": o.event, "quantile": o.quantile,
            "value": None if value is None else round(value, 3),
            "threshold": o.threshold, "unit": o.unit,
            "count": h.count, "ok": ok,
        })
    return rows


def evaluate_bench_record(record: dict, objectives) -> list:
    """Evaluate objectives against one bench/devhub record (offline —
    the devhub panel's per-run data point). Serving-window objectives
    read the record's per-window latency histogram
    (serving_batch_latency.histogram, milliseconds); anything the
    record does not carry evaluates to ok=None. Device-telemetry
    objectives (device_exchange_occupancy — the exchange-headroom burn
    early warning) read the shard probe's harvested distribution
    (shard_balance.telemetry.exchange_occupancy, already in the
    event's declared unit)."""
    lat = record.get("serving_batch_latency") or {}
    hist = None
    if isinstance(lat.get("histogram"), dict):
        try:
            hist = Histogram.from_dict(lat["histogram"])
        except (AssertionError, ValueError, TypeError):
            hist = None
    tel = (record.get("shard_balance") or {}).get("telemetry") or {}
    tel_hist = None
    if isinstance(tel.get("exchange_occupancy"), dict):
        try:
            tel_hist = Histogram.from_dict(tel["exchange_occupancy"])
        except (AssertionError, ValueError, TypeError):
            tel_hist = None
    rows = []
    for o in objectives:
        value = None
        count = 0
        if o.event == "window_commit":
            if hist is not None:
                value = hist.quantile(o.quantile)  # already ms
                count = hist.count
            elif o.quantile == 0.99 and lat.get("p99_ms") is not None:
                value = float(lat["p99_ms"])
        elif o.event == "device_exchange_occupancy" \
                and tel_hist is not None:
            value = tel_hist.quantile(o.quantile)  # already pct
            count = tel_hist.count
        ok = None if value is None else bool(value <= o.threshold)
        rows.append({
            "name": o.name, "event": o.event, "quantile": o.quantile,
            "value": None if value is None else round(value, 3),
            "threshold": o.threshold, "unit": o.unit,
            "count": count, "ok": ok,
        })
    return rows


def burn_rates(per_run_rows: list, window_runs: int,
               budget: float) -> dict:
    """Run-granular burn accounting: `per_run_rows` is a list (oldest
    first) of evaluate()/evaluate_bench_record() outputs, one per run.
    Returns {objective: {burn_rate, breaches, evaluated, budget,
    breached_now, badge}} over the trailing `window_runs` runs; runs
    where the objective was unknown don't consume error budget."""
    out: dict = {}
    recent = per_run_rows[-window_runs:]
    names = {r["name"] for rows in recent for r in rows}
    for name in sorted(names):
        verdicts = [r["ok"] for rows in recent for r in rows
                    if r["name"] == name and r["ok"] is not None]
        breaches = sum(1 for v in verdicts if v is False)
        burn = round(breaches / len(verdicts), 4) if verdicts else 0.0
        breached_now = bool(verdicts) and verdicts[-1] is False
        out[name] = {
            "burn_rate": burn, "breaches": breaches,
            "evaluated": len(verdicts), "window_runs": window_runs,
            "budget": budget, "breached_now": breached_now,
            "badge": breached_now or burn > budget,
        }
    return out
