"""Typed trace-event catalog: every legal span, counter, and gauge.

reference: src/trace/event.zig — the reference compiles a closed event
catalog into every hot path (commit stages, storage, grid, message bus)
and derives both the Chrome-trace lanes and the StatsD metric names from
it. Here the catalog is the single source of truth for:

- **legal names**: under the recording `Tracer` a span/counter/gauge
  whose name is not a catalog member is a HARD error (free-form strings
  cannot ship — scripts/gate.py's coverage leg additionally fails when a
  catalog member is never emitted by the smokes, so dead metrics cannot
  ship either);
- **fixed tag schemas**: each event declares its legal tag keys; an
  out-of-schema tag is an error, which bounds metric cardinality at the
  call site instead of in the aggregation backend;
- **stable Chrome `tid` lanes**: each span event owns a fixed lane range
  (`TID_BASE[event] .. +slots`), so overlapping occurrences (e.g. two
  in-flight block repairs) render on stable per-event lanes in any trace
  from any build (event.zig derives its tids the same way).

The catalog is append-oriented: renaming/removing an event breaks the
continuity of its StatsD series, so prefer adding. Every event listed
here is exercised by the gate's trace-coverage leg
(tigerbeetle_tpu/testing/trace_coverage.py); docs/operating/monitoring.md
is the operator-facing rendering of this table.
"""

from __future__ import annotations

import dataclasses
import enum


class EventKind(enum.Enum):
    span = "span"
    counter = "counter"
    gauge = "gauge"
    histogram = "histogram"


@dataclasses.dataclass(frozen=True)
class EventSpec:
    kind: EventKind
    tags: tuple = ()
    slots: int = 1  # concurrency lanes (spans only)
    doc: str = ""
    # Histogram partition dimensions: the subset of `tags` whose values
    # split this event's distribution into separate series (bounded
    # cardinality — route/tier class labels, never ids). Every span
    # event owns a duration histogram (fed at span close); hist_tags
    # empty means one series per event.
    hist_tags: tuple = ()


def _span(doc: str, *tags: str, slots: int = 1,
          hist_tags: tuple = ()) -> EventSpec:
    assert set(hist_tags) <= set(tags), (hist_tags, tags)
    return EventSpec(EventKind.span, tuple(tags), slots, doc,
                     tuple(hist_tags))


def _counter(doc: str, *tags: str) -> EventSpec:
    return EventSpec(EventKind.counter, tuple(tags), 1, doc)


def _gauge(doc: str, *tags: str) -> EventSpec:
    return EventSpec(EventKind.gauge, tuple(tags), 1, doc)


def _histogram(doc: str, *tags: str) -> EventSpec:
    """A standalone distribution metric (observed via Tracer.observe,
    unit declared in the doc line) — the third metric kind beside
    counters and gauges; span events get duration histograms for free."""
    return EventSpec(EventKind.histogram, tuple(tags), 1, doc,
                     tuple(tags))


class Event(enum.Enum):
    """The catalog. Member name == Chrome span name == StatsD metric
    name (under the `tb_tpu.` prefix)."""

    # ----------------------------------------------- replica commit stages
    commit_prefetch = _span(
        "journal read of the next committable prepare", "op")
    commit_execute = _span(
        "state-machine execution of one prepare or one aggregated "
        "commit window", "op", "operation", "window")
    commit_compact = _span(
        "durable flush of the committed op + one compaction beat", "op")
    commit_checkpoint = _span(
        "forest checkpoint + superblock flip", "op")
    commits = _counter("prepares committed")
    commit_windows = _counter("aggregated multi-prepare window commits")
    rollbacks = _counter("checkpoint rollbacks on divergence detection")

    # ------------------------------------------------------------- journal
    journal_write = _span("WAL prepare+header pair write (submit)", "op")
    journal_recover = _span("full WAL two-ring recovery scan")

    # ---------------------------------------------------------------- grid
    grid_scrub_tick = _span("one paced scrubber tick of block reads")
    grid_scrub_certify = _span(
        "unpaced full scrub tour (post-rebuild certification)")
    grid_repair_block = _span(
        "peer-provided block validated and installed over a corrupt one",
        slots=4)

    # -------------------------------------------- view change / sync / rebuild
    view_change = _span("view change, start to new-view adoption", "view")
    state_sync = _span("checkpoint state sync, offer to install",
                       "target_op")
    rebuild = _span("rebuild-from-cluster, open_rebuild to voter re-entry")

    # --------------------------------------------------------- message bus
    # `csum` is the frame's header-checksum low bits: the SAME value is
    # tagged on the sender's bus_send and the receiver's bus_recv, which
    # is how trace/merge.py matches send/recv pairs across pids to
    # estimate per-pid clock offsets before causal assembly.
    bus_send = _span("serialize + enqueue one outbound message",
                     "command", "csum")
    bus_recv = _span("deliver one validated inbound message",
                     "command", "csum")
    bus_pool_used = _gauge("outbound message-pool slots in use")
    config_mismatch_peer = _counter(
        "pings rejected for a cluster-config fingerprint mismatch")

    # ------------------------------------------------------------- serving
    serving_dispatch = _span(
        "one supervised device dispatch (includes retries)", "what")
    serving_epoch_verify = _span(
        "epoch verification: quiesce + oracle replay + digest + audit")
    serving_recovery_replay = _span(
        "quarantine + bounded oracle replay + device rebuild", "cause")
    serving_retries = _counter("device dispatch retries")
    serving_recoveries = _counter("serving recoveries", "cause")
    dispatch_route = _counter(
        "window/batch dispatches by kernel route (chain = the default "
        "scan-form whole-window route)", "route")
    window_commit = _span(
        "one serving commit window, submit to resolve, tagged with the "
        "dispatch route it took and its shape tier (scan = the chain "
        "whole-window scan, flat = an unrolled super route, fallback = "
        "per-batch) — the per-class latency distributions the SLO "
        "engine reads", "route", "tier", hist_tags=("route", "tier"))
    window_stage = _span(
        "host-side staging of one commit window's stacked operands "
        "(numpy pack + pytree device transfer): overlapped = packed on "
        "the staging worker while the previous window's dispatch was "
        "in flight (the recorded duration is the WAIT the dispatch "
        "path actually paid, usually ~0), inline = packed "
        "synchronously on the dispatch path (the duration is the full "
        "pack+transfer cost)", "mode", "route", hist_tags=("mode",))
    host_stall_fraction = _gauge(
        "fraction of host window-staging work the dispatch path "
        "actually waited on, cumulative per ledger (stall_ms / total "
        "staging work): 1.0 = fully synchronous staging (every pack "
        "blocks the dispatch), ~0 = the pack/transfer fully hidden "
        "behind in-flight device execution — the overlap gate leg's "
        "ceiling reads this")
    serving_replay_windows = _histogram(
        "windows replayed per recovery (unit: windows; the bounded-"
        "replay objective in perf/slo.json reads this distribution)")
    slo_breach = _counter(
        "SLO objectives observed in breach at evaluation "
        "(trace/slo.py against perf/slo.json)", "objective")

    # ------------------------------------------------------ sharded router
    router_step = _span("one sharded (or degraded single-chip) batch step",
                        "mode", "degraded")
    router_fallback = _counter("host fallbacks off the sharded step",
                               "cause")
    router_reroute = _counter(
        "batches rerouted to the single-chip step under shard loss")
    shard_exchange = _span(
        "partitioned-state batch step: on-device event exchange + "
        "per-shard fixpoint + owner-masked write-back", "mode")
    cross_shard_transfers = _counter(
        "created transfers whose debit and credit accounts live on "
        "different shards (resolved via the exchange join)")
    reshard_stage = _span(
        "one stage of a live resharding migration (parallel/"
        "resharding.py five-stage protocol): stage is snapshot|copy|"
        "double_write|flip|retire, outcome is ok|abort — an abort "
        "freezes a flight artifact and reverts the overlay",
        "stage", "outcome")
    reshard_rows_copied = _counter(
        "account+transfer rows streamed source->target by the copy "
        "stage of a resharding migration (chunked; counted per chunk)")
    reshard_overlay_active = _gauge(
        "overlay entries currently active in the ownership table "
        "(0 = base map only; >0 = a migration is between its first "
        "double-write window and its retire/flip)")

    # ----------------------------------------------------- device telemetry
    # Decoded host-side from the fixed-layout u32 telemetry block the
    # partitioned route harvests with its outputs (parallel/partitioned
    # TEL_LAYOUT): measured ON DEVICE per prepare, never host-side
    # guesswork.
    device_fixpoint_rounds = _histogram(
        "fixpoint rounds the judge actually consumed per prepare "
        "(unit: rounds; 0 = the proof-gated plain tier)")
    device_poison_cause = _counter(
        "prepares poisoned/escalated on device, by decoded cause code",
        "cause")
    device_exchange_occupancy = _histogram(
        "exchange-lane occupancy per psum phase (unit: pct of the "
        "static lane capacity; the headroom-burn early-warning "
        "objective in perf/slo.json reads this distribution)", "phase")
    device_ring_occupancy = _histogram(
        "per-shard event-ring rows after write-back (unit: rows)")
    device_writeback_rows = _counter(
        "owner-masked transfer rows written back across all shards")
    flight_recorder_dump = _counter(
        "flight-recorder artifacts dumped for post-mortem", "reason")

    # ------------------------------------------------------ admission plane
    # ISSUE 18: session ingress + SLO-driven load shedding in front of
    # the serving supervisor (tigerbeetle_tpu/admission.py). `decision`
    # is admit|shed; `cls` is the priority class (critical/standard/
    # batch by default); `reason` is the shed cause (no_credit,
    # queue_full, shed_line, deadline, drain) and is omitted on admits.
    # The span duration is the request's QUEUE WAIT (enqueue to window
    # dispatch for admits, enqueue to rejection for sheds) on the
    # plane's clock — the per-class admitted-latency distributions the
    # SLO engine's admission objectives read.
    admission_decision = _span(
        "one admission decision: request enqueue to window dispatch "
        "(admit) or to typed ShedResult (shed); duration = queue wait "
        "on the plane clock", "decision", "cls", "reason",
        hist_tags=("decision", "cls"))
    admission_shed = _counter(
        "requests rejected with a typed ShedResult", "cls", "reason")
    admission_credit_occupancy = _gauge(
        "admission queue occupancy, 0..1 of the plane's bounded queue "
        "capacity (sampled once per pump tick)")

    # -------------------------------------------------- causal tracing
    # ISSUE 15: per-request spans.  These carry a propagated trace
    # context (trace_id/span_id/parent_id recorded as span args), so
    # trace/merge.py's assemble_traces() can rebuild one causal tree
    # per client request across client + replica dumps.
    client_request = _span(
        "one client request, submit to reply (the causal root span "
        "every downstream span parents to)", "operation")
    commit_quorum = _span(
        "primary's prepare_ok quorum wait: prepare fan-out to quorum "
        "reached (explicit-timing span recorded at quorum)", "op")
    replica_ack = _span(
        "backup replication of one traced prepare: receipt to the "
        "durable-slot prepare_ok", "op")
    trace_tail_keep = _counter(
        "traces force-kept by tail retention (SLO breach, fallback/"
        "poison cause, supervisor recovery) regardless of the head-"
        "sampling decision", "reason")

    # ------------------------------------------- performance observatory
    # ISSUE 20: sampled dispatch profiling, device-memory watermarks,
    # and burn-rate alerting (trace/profiler.py, trace/memwatch.py,
    # trace/alerts.py). `dispatch_device_time` is the profiler's
    # measured device time of one SAMPLED dispatch (block-until-ready
    # timer, or a jax.profiler capture where the backend supports it);
    # the memory gauges are the host-side static-allocation ledger's
    # watermark vs the committed perf/membudget_r*.json; `alert_fired`
    # counts typed alert firings from the multi-window burn-rate engine.
    dispatch_device_time = _histogram(
        "device time of one sampled serving dispatch (unit: us; "
        "sampled 1/N by trace/profiler.py DispatchProfiler, partitioned "
        "by dispatch route and shape tier — the measured side of the "
        "achieved-vs-roofline fraction)", "route", "tier")
    memory_watermark_bytes = _gauge(
        "static-allocation ledger watermark: bytes the serving ledger "
        "holds resident (state pytree + staged packs + telemetry block "
        "+ scratch), summed across components by trace/memwatch.py — "
        "checked against the committed perf/membudget_r*.json")
    memory_budget_headroom_bytes = _gauge(
        "committed memory budget minus the current watermark (negative "
        "= over budget, the memwatch gate leg REDs)")
    alert_fired = _counter(
        "typed alerts fired by the multi-window burn-rate engine "
        "(trace/alerts.py), by rule and severity; a page-severity "
        "firing freezes a flight-recorder artifact and tail-keeps the "
        "breaching traces under reason alert:<rule>", "rule", "severity")

    # ------------------------------------------------------ tracer internal
    trace_dropped_events = _counter(
        "span ring evictions (the trace is truncated at its start)")

    @property
    def kind(self) -> EventKind:
        return self.value.kind

    @property
    def tags(self) -> tuple:
        return self.value.tags

    @property
    def slots(self) -> int:
        return self.value.slots

    @property
    def doc(self) -> str:
        return self.value.doc

    @property
    def hist_tags(self) -> tuple:
        return self.value.hist_tags


CATALOG: dict = {e.name: e for e in Event}

# Stable Chrome lanes: tid 0 is reserved for instant markers/metadata;
# each span event owns [TID_BASE[e], TID_BASE[e] + e.slots).
TID_BASE: dict = {}
_next = 1
for _e in Event:
    TID_BASE[_e] = _next
    if _e.kind == EventKind.span:
        _next += _e.slots

# Hot-path constants, stapled onto each member as a PLAIN instance
# attribute: `ev._hot` is one C-speed attribute read, where `ev.name`
# costs a DynamicClassAttribute descriptor hop, `ev.tags` a property
# into the EventSpec, and any dict keyed by the member a Python-level
# Enum.__hash__ call. The recording tracer's span-close path reads
# several of these per span; the bench ##trace overhead ratios guard
# the sum. Layout: (name, kind, frozenset(tags), slots, hist_tags,
# TID_BASE[member]).
for _e in Event:
    _e._hot = (_e.name, _e.kind, frozenset(_e.tags), _e.slots,
               _e.hist_tags, TID_BASE[_e])
del _next, _e


def lookup(name) -> Event:
    """Resolve an Event member or its string name; KeyError text names
    the offender (the recording tracer's hard-error path)."""
    if isinstance(name, Event):
        return name
    ev = CATALOG.get(name)
    if ev is None:
        raise KeyError(
            f"trace event {name!r} is not in the catalog "
            f"(tigerbeetle_tpu/trace/event.py); free-form names are "
            f"rejected under the recording tracer")
    return ev
