"""Tracing and metrics subsystem: typed catalog, spans, StatsD, merge.

reference: src/trace.zig + src/trace/event.zig + src/trace/statsd.zig.
Layout mirrors the reference:

- `event.py`  — the typed event catalog (every legal span/counter/gauge,
  fixed tag schemas, per-event concurrency lanes). Free-form names are a
  hard error under the recording tracer; the gate's coverage leg fails
  on catalog events the smokes never emit.
- `tracer.py` — NullTracer (production default, zero overhead) and the
  recording Tracer (bounded ring with self-describing eviction,
  wall-clock-anchored timestamps, per-event timing aggregates).
- `statsd.py` — DogStatsD UDP emission + interval-flushed aggregates
  (gauges reset after emit, like the reference).
- `merge.py`  — cluster-wide trace merge (pid=replica, common timeline).

The tracer is injected at construction into the replica, journal, grid
scrubber, message bus, serving supervisor, and sharded router; see
docs/operating/monitoring.md for the operator-facing catalog.
"""

from .event import CATALOG, TID_BASE, Event, EventKind, EventSpec, lookup
from .merge import merge_trace_files, merge_traces
from .statsd import StatsD, TimingAggregates
from .tracer import NullTracer, Tracer

__all__ = [
    "CATALOG", "TID_BASE", "Event", "EventKind", "EventSpec", "lookup",
    "merge_trace_files", "merge_traces", "StatsD", "TimingAggregates",
    "NullTracer", "Tracer",
]
