"""Tracing and metrics subsystem: typed catalog, spans, StatsD, merge.

reference: src/trace.zig + src/trace/event.zig + src/trace/statsd.zig.
Layout mirrors the reference:

- `event.py`  — the typed event catalog (every legal span/counter/gauge,
  fixed tag schemas, per-event concurrency lanes). Free-form names are a
  hard error under the recording tracer; the gate's coverage leg fails
  on catalog events the smokes never emit.
- `tracer.py` — NullTracer (production default, zero overhead) and the
  recording Tracer (bounded ring with self-describing eviction,
  wall-clock-anchored timestamps, per-event timing aggregates).
- `statsd.py` — DogStatsD UDP emission + interval-flushed aggregates
  (gauges reset after emit, like the reference) with histogram-derived
  p50/p95/p99/p999 `|ms` timing lines per series.
- `histogram.py` — log2-bucketed, losslessly mergeable latency
  histograms (~1% relative error), fed by every span at close.
- `merge.py`  — cluster-wide trace merge (pid=replica, common timeline),
  exact offline span quantiles, and p99 critical-path attribution.
- `slo.py`    — objectives from perf/slo.json, evaluation against live
  histograms, and run-granular burn-rate accounting.
- `flight_recorder.py` — bounded per-replica ring of per-window device
  telemetry + route decisions + epoch digests, dumped as a JSON
  artifact on quarantine/recovery/retry-exhaustion, with lossless
  cross-replica merge via the shared histogram layout.

The tracer is injected at construction into the replica, journal, grid
scrubber, message bus, serving supervisor, and sharded router; see
docs/operating/monitoring.md for the operator-facing catalog.
"""

from .event import CATALOG, TID_BASE, Event, EventKind, EventSpec, lookup
from .flight_recorder import FlightRecorder, merge_flight_records
from .histogram import Histogram
from .merge import (CRITICAL_PATH_STAGES, critical_path, merge_trace_files,
                    merge_traces, span_quantile)
from .slo import (Objective, burn_rates, evaluate, evaluate_bench_record,
                  load_objectives)
from .statsd import StatsD, TimingAggregates
from .tracer import NullTracer, Tracer

__all__ = [
    "CATALOG", "TID_BASE", "Event", "EventKind", "EventSpec", "lookup",
    "FlightRecorder", "merge_flight_records",
    "Histogram", "CRITICAL_PATH_STAGES", "critical_path",
    "merge_trace_files", "merge_traces", "span_quantile",
    "Objective", "burn_rates", "evaluate", "evaluate_bench_record",
    "load_objectives", "StatsD", "TimingAggregates",
    "NullTracer", "Tracer",
]
