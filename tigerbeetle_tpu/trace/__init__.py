"""Tracing and metrics subsystem: typed catalog, spans, StatsD, merge.

reference: src/trace.zig + src/trace/event.zig + src/trace/statsd.zig.
Layout mirrors the reference:

- `event.py`  — the typed event catalog (every legal span/counter/gauge,
  fixed tag schemas, per-event concurrency lanes). Free-form names are a
  hard error under the recording tracer; the gate's coverage leg fails
  on catalog events the smokes never emit.
- `tracer.py` — NullTracer (production default, zero overhead) and the
  recording Tracer (bounded ring with self-describing eviction,
  wall-clock-anchored timestamps, per-event timing aggregates).
- `statsd.py` — DogStatsD UDP emission + interval-flushed aggregates
  (gauges reset after emit, like the reference) with histogram-derived
  p50/p95/p99/p999 `|ms` timing lines per series.
- `histogram.py` — log2-bucketed, losslessly mergeable latency
  histograms (~1% relative error), fed by every span at close.
- `merge.py`  — cluster-wide trace merge (pid=replica, common timeline),
  exact offline span quantiles, p99 critical-path attribution, and
  causal assembly: per-request span trees from propagated trace
  contexts, with clock-skew correction from matched bus span pairs.
- `context.py` — the compact trace-context block (trace_id u128,
  parent_span_id u64, sampled flag) carried in the VSR header's
  reserved region, plus deterministic minting and head sampling.
- `slo.py`    — objectives from perf/slo.json, evaluation against live
  histograms, and run-granular burn-rate accounting.
- `flight_recorder.py` — bounded per-replica ring of per-window device
  telemetry + route decisions + epoch digests, dumped as a JSON
  artifact on quarantine/recovery/retry-exhaustion, with lossless
  cross-replica merge via the shared histogram layout.
- `profiler.py` — the performance observatory's dispatch side: sampled
  block-until-ready dispatch timing (`dispatch_device_time`), optional
  programmatic jax.profiler capture, and the static FLOPs/HBM-bytes
  cost model + achieved-vs-roofline fractions per dispatch tier.
- `memwatch.py` — device-memory watermark plane: the deterministic
  static-allocation ledger (bytes per component from shapes) audited
  against the committed perf/membudget_r*.json, plus per-device
  allocator stats where the backend exposes them.
- `alerts.py`  — SRE-style multi-window multi-burn-rate alert engine
  over the SLO objectives, in commit-window-tick time: typed alerts
  with runbook anchors, `alert:<rule>` tail retention, and page-
  severity flight-recorder freezes.

The tracer is injected at construction into the replica, journal, grid
scrubber, message bus, serving supervisor, and sharded router; see
docs/operating/monitoring.md for the operator-facing catalog.
"""

from .alerts import Alert, AlertEngine, AlertRule, load_alert_rules
from .context import (TraceContext, fmt_span_id, fmt_trace_id,
                      head_sampled, mint_context, mint_trace_id)
from .event import CATALOG, TID_BASE, Event, EventKind, EventSpec, lookup
from .flight_recorder import FlightRecorder, merge_flight_records
from .histogram import Histogram
from .memwatch import (MemWatch, check_budget, device_memory_stats,
                       load_budget, measure_ledger, pytree_bytes,
                       static_ledger)
from .merge import (CRITICAL_PATH_STAGES, assemble_traces, causal_edges,
                    critical_path, estimate_clock_offsets,
                    merge_trace_files, merge_traces, span_quantile)
from .profiler import (DispatchProfiler, measured_dispatch_us,
                       profile_probe, roofline_fractions,
                       roofline_seconds, static_cost_model)
from .slo import (Objective, burn_rates, evaluate, evaluate_bench_record,
                  load_objectives)
from .statsd import StatsD, TimingAggregates
from .tracer import NullTracer, Tracer

__all__ = [
    "CATALOG", "TID_BASE", "Event", "EventKind", "EventSpec", "lookup",
    "TraceContext", "fmt_span_id", "fmt_trace_id", "head_sampled",
    "mint_context", "mint_trace_id",
    "FlightRecorder", "merge_flight_records",
    "Histogram", "CRITICAL_PATH_STAGES", "critical_path",
    "assemble_traces", "causal_edges", "estimate_clock_offsets",
    "merge_trace_files", "merge_traces", "span_quantile",
    "Objective", "burn_rates", "evaluate", "evaluate_bench_record",
    "load_objectives", "StatsD", "TimingAggregates",
    "NullTracer", "Tracer",
    "Alert", "AlertEngine", "AlertRule", "load_alert_rules",
    "MemWatch", "check_budget", "device_memory_stats", "load_budget",
    "measure_ledger", "pytree_bytes", "static_ledger",
    "DispatchProfiler", "measured_dispatch_us", "profile_probe",
    "roofline_fractions", "roofline_seconds", "static_cost_model",
]
